// Native batch JPEG decoder — the TPU-native equivalent of the reference's
// only native component (upstream pylance's Rust decode path; SURVEY.md §2.2).
//
// Replaces the per-row Python/PIL hot loop the reference runs inside the
// training process (/root/reference/lance_iterable.py:38-50, single-threaded
// because num_workers is forced to 0 under DDP, :75-77) with:
//   * libjpeg decode with DCT scaling (decode directly at 1/2, 1/4, 1/8 when
//     the target is smaller — skips most of the IDCT work),
//   * fixed-point bilinear resize to the target square,
//   * a C++ thread pool: true parallelism, no GIL, writing each image
//     straight into its slot of the caller-provided NHWC uint8 batch buffer
//     (which the input pipeline then hands to jax.device_put for TPU DMA).
//
// Build: g++ -O3 -march=native -shared -fPIC ldt_decode.cpp -ljpeg
// C ABI only; bound from Python via ctypes (no pybind11 in this image).

#include <atomic>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Bilinear resize RGB u8, src (sw x sh) -> dst (dw x dh). Fixed-point 16.16.
void resize_bilinear(const uint8_t* src, int sw, int sh, uint8_t* dst, int dw,
                     int dh) {
  const int64_t x_ratio = ((int64_t)(sw - 1) << 16) / (dw > 1 ? dw - 1 : 1);
  const int64_t y_ratio = ((int64_t)(sh - 1) << 16) / (dh > 1 ? dh - 1 : 1);
  for (int y = 0; y < dh; ++y) {
    const int64_t sy_fix = y * y_ratio;
    const int sy = (int)(sy_fix >> 16);
    const int wy = (int)(sy_fix & 0xFFFF);
    const int sy1 = sy + 1 < sh ? sy + 1 : sy;
    const uint8_t* row0 = src + (size_t)sy * sw * 3;
    const uint8_t* row1 = src + (size_t)sy1 * sw * 3;
    uint8_t* out = dst + (size_t)y * dw * 3;
    for (int x = 0; x < dw; ++x) {
      const int64_t sx_fix = x * x_ratio;
      const int sx = (int)(sx_fix >> 16);
      const int wx = (int)(sx_fix & 0xFFFF);
      const int sx1 = sx + 1 < sw ? sx + 1 : sx;
      for (int c = 0; c < 3; ++c) {
        const int p00 = row0[sx * 3 + c], p01 = row0[sx1 * 3 + c];
        const int p10 = row1[sx * 3 + c], p11 = row1[sx1 * 3 + c];
        const int64_t top = ((int64_t)p00 << 16) + (int64_t)(p01 - p00) * wx;
        const int64_t bot = ((int64_t)p10 << 16) + (int64_t)(p11 - p10) * wx;
        const int64_t val = (top << 16) + (bot - top) * wy;  // 32.32
        out[x * 3 + c] = (uint8_t)(val >> 32);
      }
    }
  }
}

// Decode one JPEG into dst (out_size x out_size x 3 u8). Returns 0 on success.
int decode_one(const uint8_t* data, size_t len, int out_size, uint8_t* dst,
               std::vector<uint8_t>& scratch) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data), (unsigned long)len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  // DCT scaling: pick the largest denominator whose output still covers the
  // target (the same trick as PIL draft / libjpeg-turbo tjscalingfactors).
  cinfo.scale_num = 1;
  cinfo.scale_denom = 1;
  for (int denom = 8; denom > 1; denom /= 2) {
    if ((int)cinfo.image_width / denom >= out_size &&
        (int)cinfo.image_height / denom >= out_size) {
      cinfo.scale_denom = denom;
      break;
    }
  }
  cinfo.dct_method = JDCT_IFAST;
  cinfo.do_fancy_upsampling = FALSE;
  jpeg_start_decompress(&cinfo);
  const int sw = cinfo.output_width, sh = cinfo.output_height;
  const size_t row_bytes = (size_t)sw * cinfo.output_components;
  const bool direct =
      sw == out_size && sh == out_size && cinfo.output_components == 3;
  uint8_t* sink = dst;
  if (!direct) {
    scratch.resize(row_bytes * sh);
    sink = scratch.data();
  }
  // Already at target size: decode scanlines straight into the caller's
  // batch slot — no scratch buffer, no copy. Otherwise decode to scratch
  // and resize.
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = sink + (size_t)cinfo.output_scanline * row_bytes;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  // out_color_space was forced to JCS_RGB before jpeg_start_decompress, so
  // libjpeg itself converts grayscale/YCbCr → 3 components (unconvertible
  // color spaces longjmp to the error path). Capture before destroy.
  const int components = cinfo.output_components;
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  if (components != 3) return 2;

  if (!direct) {
    resize_bilinear(scratch.data(), sw, sh, dst, out_size, out_size);
  }
  return 0;
}

}  // namespace

extern "C" {

// Decode n JPEGs into out (n * out_size * out_size * 3, NHWC u8).
// srcs[i]/lens[i] describe image i. Returns the number of FAILED images;
// failed slots are zero-filled and flagged in failed[i] (if non-null).
int ldt_decode_batch(const uint8_t** srcs, const size_t* lens, int n,
                     int out_size, uint8_t* out, uint8_t* failed,
                     int n_threads) {
  if (n <= 0) return 0;
  const size_t img_bytes = (size_t)out_size * out_size * 3;
  if (n_threads <= 0) n_threads = (int)std::thread::hardware_concurrency();
  if (n_threads > n) n_threads = n;
  std::atomic<int> next(0), failures(0);
  auto worker = [&]() {
    std::vector<uint8_t> scratch;
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      uint8_t* dst = out + (size_t)i * img_bytes;
      int rc = decode_one(srcs[i], lens[i], out_size, dst, scratch);
      if (rc != 0) {
        std::memset(dst, 0, img_bytes);
        if (failed) failed[i] = 1;
        failures.fetch_add(1);
      } else if (failed) {
        failed[i] = 0;
      }
    }
  };
  if (n_threads <= 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failures.load();
}

// Zero-copy Arrow path: decode n JPEGs described by an Arrow binary column's
// buffers — `data` is the values buffer, `offsets[i]..offsets[i+1]` delimits
// image i (int64, as in Arrow large_binary; the Python side widens int32
// offsets). No per-row Python bytes objects are ever materialised.
int ldt_decode_batch_offsets(const uint8_t* data, const int64_t* offsets,
                             int n, int out_size, uint8_t* out,
                             uint8_t* failed, int n_threads) {
  if (n <= 0) return 0;
  const size_t img_bytes = (size_t)out_size * out_size * 3;
  if (n_threads <= 0) n_threads = (int)std::thread::hardware_concurrency();
  if (n_threads > n) n_threads = n;
  std::atomic<int> next(0), failures(0);
  auto worker = [&]() {
    std::vector<uint8_t> scratch;
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      uint8_t* dst = out + (size_t)i * img_bytes;
      const int64_t lo = offsets[i], hi = offsets[i + 1];
      int rc = (hi > lo)
                   ? decode_one(data + lo, (size_t)(hi - lo), out_size, dst,
                                scratch)
                   : 1;
      if (rc != 0) {
        std::memset(dst, 0, img_bytes);
        if (failed) failed[i] = 1;
        failures.fetch_add(1);
      } else if (failed) {
        failed[i] = 0;
      }
    }
  };
  if (n_threads <= 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failures.load();
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Entropy-boundary split (ABI v3): the host half of device-side decode.
//
// `ldt_probe_batch` parses only the JPEG headers (geometry + sampling);
// `ldt_extract_coeffs` runs jpeg_read_coefficients — the inherently
// sequential Huffman/entropy decode, with DC prediction and de-zigzag
// resolved by libjpeg — and copies the quantized DCT blocks into
// caller-provided canonical coefficient pages. Everything dense that used
// to follow here (dequant, IDCT, chroma upsample, color convert, resize)
// now runs on device as a jitted kernel (ops/jpeg_device.py).
//
// Canonical page layout (the Python side sizes the grids to the batch max,
// rounded to its chunk granularity):
//   coef_y  : int16 [n, yb_h, yb_w, 64]   natural-order blocks, zero-padded
//   coef_cb : int16 [n, cb_h, cb_w, 64]   (4:2:0 grid; zeros for grayscale)
//   coef_cr : int16 [n, cb_h, cb_w, 64]
//   quant   : int32 [n, 3, 64]            per-component dequant tables
//   geom    : int32 [n, 6]                w, h, yb_w, yb_h, cb_w, cb_h (real,
//                                         unpadded block counts)
// Supported sources: baseline/progressive, 1-component grayscale and
// 3-component with 2x2 luma sampling (the 4:2:0 every PIL/libjpeg default
// writes). Anything else (4:4:4, 4:2:2, CMYK) is flagged in failed[] and
// the Python driver re-encodes that row to 4:2:0 before retrying.

namespace {

// Probe one image: header-only parse. Returns 0 and fills
// geom4 = {width, height, ncomp, coeff_ok} on success; nonzero on parse
// failure (geom4 zeroed).
int probe_one(const uint8_t* data, size_t len, int32_t* geom4) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    geom4[0] = geom4[1] = geom4[2] = geom4[3] = 0;
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data), (unsigned long)len);
  jpeg_read_header(&cinfo, TRUE);
  geom4[0] = (int32_t)cinfo.image_width;
  geom4[1] = (int32_t)cinfo.image_height;
  geom4[2] = (int32_t)cinfo.num_components;
  int ok = 0;
  if (cinfo.num_components == 1 &&
      cinfo.jpeg_color_space == JCS_GRAYSCALE) {
    ok = 1;
  } else if (cinfo.num_components == 3 &&
             cinfo.jpeg_color_space == JCS_YCbCr &&
             cinfo.comp_info[0].h_samp_factor == 2 &&
             cinfo.comp_info[0].v_samp_factor == 2 &&
             cinfo.comp_info[1].h_samp_factor == 1 &&
             cinfo.comp_info[1].v_samp_factor == 1 &&
             cinfo.comp_info[2].h_samp_factor == 1 &&
             cinfo.comp_info[2].v_samp_factor == 1) {
    ok = 1;  // canonical 4:2:0
  }
  geom4[3] = ok;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Extract one image's quantized coefficients into its canonical page slot.
// Returns 0 on success, nonzero on failure (slot contents undefined; the
// caller zero-fills pages up front).
int extract_one(const uint8_t* data, size_t len, int yb_h, int yb_w, int cb_h,
                int cb_w, int16_t* coef_y, int16_t* coef_cb, int16_t* coef_cr,
                int32_t* quant, int32_t* geom6) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data), (unsigned long)len);
  jpeg_read_header(&cinfo, TRUE);
  const int ncomp = cinfo.num_components;
  const bool gray = ncomp == 1 && cinfo.jpeg_color_space == JCS_GRAYSCALE;
  const bool ycc420 =
      ncomp == 3 && cinfo.jpeg_color_space == JCS_YCbCr &&
      cinfo.comp_info[0].h_samp_factor == 2 &&
      cinfo.comp_info[0].v_samp_factor == 2 &&
      cinfo.comp_info[1].h_samp_factor == 1 &&
      cinfo.comp_info[1].v_samp_factor == 1 &&
      cinfo.comp_info[2].h_samp_factor == 1 &&
      cinfo.comp_info[2].v_samp_factor == 1;
  if (!gray && !ycc420) {
    jpeg_destroy_decompress(&cinfo);
    return 2;
  }
  // The entropy decode: Huffman (or arithmetic) + DC prediction +
  // de-zigzag into natural-order JBLOCKs. No IDCT, no upsample, no color.
  jvirt_barray_ptr* arrays = jpeg_read_coefficients(&cinfo);
  if (arrays == nullptr) {
    jpeg_destroy_decompress(&cinfo);
    return 3;
  }
  geom6[0] = (int32_t)cinfo.image_width;
  geom6[1] = (int32_t)cinfo.image_height;
  for (int ci = 0; ci < ncomp; ++ci) {
    jpeg_component_info* comp = &cinfo.comp_info[ci];
    const int bw = (int)comp->width_in_blocks;
    const int bh = (int)comp->height_in_blocks;
    const int grid_h = ci == 0 ? yb_h : cb_h;
    const int grid_w = ci == 0 ? yb_w : cb_w;
    if (bw > grid_w || bh > grid_h) {
      jpeg_destroy_decompress(&cinfo);
      return 4;  // caller's canonical grid too small (it probes first)
    }
    int16_t* page = ci == 0 ? coef_y : (ci == 1 ? coef_cb : coef_cr);
    for (int row = 0; row < bh; ++row) {
      JBLOCKARRAY rows = (cinfo.mem->access_virt_barray)(
          (j_common_ptr)&cinfo, arrays[ci], (JDIMENSION)row, 1, FALSE);
      int16_t* dst = page + ((size_t)row * grid_w) * 64;
      static_assert(sizeof(JCOEF) == sizeof(int16_t),
                    "JCOEF expected to be 16-bit");
      std::memcpy(dst, rows[0][0], (size_t)bw * 64 * sizeof(int16_t));
    }
    if (ci == 0) {
      geom6[2] = bw;
      geom6[3] = bh;
    } else if (ci == 1) {
      geom6[4] = bw;
      geom6[5] = bh;
    }
    JQUANT_TBL* qtbl = comp->quant_table != nullptr
                           ? comp->quant_table
                           : cinfo.quant_tbl_ptrs[comp->quant_tbl_no];
    if (qtbl == nullptr) {
      jpeg_destroy_decompress(&cinfo);
      return 5;
    }
    for (int k = 0; k < 64; ++k) quant[ci * 64 + k] = (int32_t)qtbl->quantval[k];
  }
  if (gray) {
    // Grayscale: zero chroma coefficients (pre-zeroed pages) decode to a
    // flat 128 plane — neutral chroma, so RGB == Y on device. Report the
    // canonical half-res chroma geometry and copy the luma quant table so
    // the page is self-consistent.
    geom6[4] = (geom6[2] + 1) / 2;
    geom6[5] = (geom6[3] + 1) / 2;
    for (int k = 0; k < 64; ++k) {
      quant[1 * 64 + k] = quant[k];
      quant[2 * 64 + k] = quant[k];
    }
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

}  // namespace

extern "C" {

// Header-only probe of n JPEGs: geom[i*4..] = {w, h, ncomp, coeff_ok};
// failed[i] = 1 on unparsable headers. Returns the failure count.
int ldt_probe_batch(const uint8_t** srcs, const size_t* lens, int n,
                    int32_t* geom, uint8_t* failed) {
  int failures = 0;
  for (int i = 0; i < n; ++i) {
    int rc = probe_one(srcs[i], lens[i], geom + (size_t)i * 4);
    if (failed) failed[i] = rc != 0 ? 1 : 0;
    if (rc != 0) ++failures;
  }
  return failures;
}

// Entropy-decode n JPEGs into canonical coefficient pages (layout in the
// header comment above; pages must be ZEROED by the caller — padding blocks
// are never written). Returns the number of FAILED images; failed[i] is set
// and that image's page contents are unspecified (still within bounds).
int ldt_extract_coeffs(const uint8_t** srcs, const size_t* lens, int n,
                       int yb_h, int yb_w, int cb_h, int cb_w,
                       int16_t* coef_y, int16_t* coef_cb, int16_t* coef_cr,
                       int32_t* quant, int32_t* geom, uint8_t* failed,
                       int n_threads) {
  if (n <= 0) return 0;
  const size_t y_page = (size_t)yb_h * yb_w * 64;
  const size_t c_page = (size_t)cb_h * cb_w * 64;
  if (n_threads <= 0) n_threads = (int)std::thread::hardware_concurrency();
  if (n_threads > n) n_threads = n;
  std::atomic<int> next(0), failures(0);
  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      int rc = extract_one(srcs[i], lens[i], yb_h, yb_w, cb_h, cb_w,
                           coef_y + (size_t)i * y_page,
                           coef_cb + (size_t)i * c_page,
                           coef_cr + (size_t)i * c_page, quant + (size_t)i * 192,
                           geom + (size_t)i * 6);
      if (rc != 0) {
        if (failed) failed[i] = 1;
        failures.fetch_add(1);
      } else if (failed) {
        failed[i] = 0;
      }
    }
  };
  if (n_threads <= 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failures.load();
}

// Version tag so the Python side can detect stale builds.
int ldt_decode_abi_version() { return 3; }

}  // extern "C"
