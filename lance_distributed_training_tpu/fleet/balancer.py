"""``FleetLoader`` — one trainer shard striped across N data servers.

Drop-in replacement for :class:`~..service.client.RemoteLoader` that takes a
*coordinator* address instead of a server address: it resolves the live
membership, takes THIS training process's deterministic slice of it
(:func:`members_for_process` — fleet stripes map onto
``jax.process_index()``, so each host fetches exactly its shard of the
global batch and no server ships redundant bytes to two hosts), opens one
protocol-v3 stream per assigned member with ``stripe_index/stripe_count``
HELLOs (member ``i`` of ``n`` serves exactly the plan steps ``s % n == i``),
and merges the streams back into plan order — so the yielded batch sequence
is **bit-identical** to a single ``RemoteLoader`` against one server, while
decode bandwidth scales with the fleet.

Failover model (the reason this class exists): the merge loop owns a single
global cursor — the first step not yet handed to the consumer. When any
stripe's connection dies (server crash, network cut), the whole round is
torn down (buffered-but-unyielded batches released back to the pool),
membership is re-resolved with the dead address excluded, and a fresh set
of stripes is opened with ``start_step = cursor`` over the survivors. Every
step below the cursor was already delivered exactly once; every step at or
above it is served exactly once by the new striping — no loss, no
duplication, the ``RemoteLoader`` contract preserved across server loss.

A *stall* is not a failure: mid-stream receives carry no deadline (same
policy as ``RemoteLoader`` — a slow decode must not be misread as a dead
peer), so a stalled server just holds its stripe's consumer until TCP or a
real disconnect says otherwise.

Coordinator loss degrades discovery, not the stream in flight: resolution
is only needed at iteration start and at failover, and both retry with
backoff.
"""

from __future__ import annotations

import queue
import socket
import threading
import time
import uuid
from collections import deque
from typing import Callable, Iterator, Optional, Sequence

from ..obs.lineage import observe_wire_lineage
from ..obs.registry import MetricsRegistry, default_registry
from ..obs.spans import span
from ..obs.tracectx import child, coerce_trace
from ..tune.tunable import AdjustableQueue, Tunable, _LiveQueues
from ..utils.metrics import ServiceCounters
from ..utils.retry import RetryPolicy, retrying
from ..service import protocol as P

__all__ = ["FleetLoader", "members_for_process", "resolve_fleet"]


def resolve_fleet(coordinator_addr: str, timeout_s: float = 10.0,
                  job_id: Optional[str] = None,
                  job_priority: Optional[str] = None) -> dict:
    """One RESOLVE round-trip: the coordinator's membership payload —
    generation, stripe table, per-member heartbeat-reported pressure,
    per-job registry rows, and the scale recommendation. Shared by
    :class:`FleetLoader`, ``ldt fleet recommend`` and ``ldt jobs`` (the
    operator's views of the same answer). ``job_id``/``job_priority``
    ride the RESOLVE request (v6: they declare the caller's job to the
    coordinator's registry; null = undeclared, and pre-v6 coordinators
    ignore unknown fields, so the declaration is downgrade-safe by
    construction)."""
    host, port = P.parse_hostport(coordinator_addr)
    timeout_s = min(float(timeout_s), 10.0)
    with socket.create_connection((host, port), timeout=timeout_s) as sock:
        P.send_msg(sock, P.MSG_FLEET_RESOLVE, {
            "job_id": job_id,
            "job_priority": job_priority,
        })
        msg_type, reply = P.recv_msg(
            sock, deadline=time.monotonic() + timeout_s
        )
    if msg_type != P.MSG_FLEET_RESOLVE_OK:
        raise P.ProtocolError(
            f"coordinator answered message type {msg_type}: "
            f"{reply.get('message', '')}"
        )
    return reply

_SENTINEL = object()
_STRIPE_END = object()


def members_for_process(members: list, process_index: int,
                        process_count: int) -> list:
    """Deterministic, disjoint member→training-process assignment.

    Multi-host training used to have every jax process stripe over the
    WHOLE fleet: with P hosts and N servers, each server decoded and
    shipped P different shards' stripes — P× the connections and redundant
    wire bytes per member. Instead, process ``p`` of ``P`` takes a
    contiguous balanced slice of the ``server_id``-sorted member list, so
    each host fetches exactly its shard of the global batch from its own
    members and no server serves two hosts (when ``len(members) >= P``).

    Properties (pinned by ``tests/test_placement.py``): deterministic in
    the sorted member order; slices are disjoint and cover every member;
    sizes differ by at most one. With fewer members than processes the
    fleet cannot be partitioned — processes then share members round-robin
    (correctness holds: each process still requests only its own shard's
    plan in the HELLO, a shared member just serves two plans).
    """
    n = len(members)
    if n == 0 or process_count <= 1:
        return list(members)
    if n < process_count:
        return [members[process_index % n]]
    base, extra = divmod(n, process_count)
    start = process_index * base + min(process_index, extra)
    stop = start + base + (1 if process_index < extra else 0)
    return list(members[start:stop])


class _StripeFailure(Exception):
    """A member's data stream failed (connect or mid-stream) — the signal
    that triggers a failover round, never surfaced to the consumer."""

    def __init__(self, addr: str, cause: Exception):
        super().__init__(f"{addr}: {cause}")
        self.addr = addr
        self.cause = cause


class _StripeRound:
    """One striping of the plan's remaining steps over a member list.

    Owns one socket + pump thread + bounded queue per member; the merge
    loop (:meth:`next_batch`) pops step ``s`` from queue ``s % n``. Lives
    until the plan completes, a stripe fails, or the loader closes.
    """

    def __init__(self, loader: "FleetLoader", members: list, cursor: int,
                 stop: threading.Event):
        self.loader = loader
        self.members = members
        self.cursor = cursor
        self.stop = stop
        self.count = len(members)
        self.queues = [
            queue.Queue(maxsize=max(1, loader.stripe_queue_depth))
            for _ in members
        ]
        self.threads: list = []
        self.socks: list = []
        self.failed = threading.Event()
        # Published BEFORE failed.set(); consumers read it only after
        # failed.is_set() — the Event's set/is_set pair orders the write
        # against every read (the same handoff discipline LDT1002 wants).
        self.failed_addr: Optional[str] = None
        self.closed = threading.Event()  # teardown flag: close() → pumps

    def connect(self) -> None:
        """Dial every member's stripe. Raises :class:`_StripeFailure` (all
        opened sockets closed) when any member is unreachable — the caller
        excludes that address and re-stripes."""
        for i, member in enumerate(self.members):
            try:
                sock = self.loader._dial_member(
                    member["addr"], self.cursor, i, self.count, self.stop
                )
            except (ConnectionError, OSError) as exc:
                self.close()
                raise _StripeFailure(member["addr"], exc)
            self.socks.append(sock)
        for i, (member, sock) in enumerate(zip(self.members, self.socks)):
            t = threading.Thread(
                target=self._pump, args=(i, member["addr"], sock),
                daemon=True, name=f"ldt-fleet-stripe-{i}",
            )
            t.start()
            self.threads.append(t)

    def _fail(self, addr: str) -> None:
        if not self.failed.is_set():
            self.failed_addr = addr  # ldt: ignore[LDT1002] -- published before failed.set(); readers gate on is_set(), so the Event orders this write
            self.failed.set()

    def _pump(self, i: int, addr: str, sock: socket.socket) -> None:
        """Receiver thread for stripe ``i``: frames → bounded queue, ACK
        each step. A connection error marks the round failed (failover); a
        protocol/server error is fatal and rides the queue to the merge
        loop."""
        loader = self.loader
        # First step of this stripe at or above the round's cursor.
        expected = self.cursor + (i - self.cursor) % self.count
        reader = P.FrameReader(sock)
        try:
            while not self.stop.is_set():
                try:
                    msg_type, payload = reader.recv_msg()
                except (ConnectionError, OSError) as exc:
                    if not (self.closed.is_set() or self.stop.is_set()):
                        self._fail(addr)
                    return
                if msg_type == P.MSG_BATCH:
                    recv_ns = time.time_ns()
                    with span("fleet.recv", step=expected,
                              stripe=i) as sp_attrs:
                        step, batch, lineage, trace = P.decode_batch(
                            payload["raw"], with_lineage=True,
                            with_trace=True, pool=loader.buffer_pool,
                        )
                        # Continue the member's causal chain (v5) — same
                        # child-hop stamping as RemoteLoader, so a merged
                        # export draws the member→merge parent edge.
                        trace = coerce_trace(trace)
                        if trace is not None:
                            hop = child(trace)
                            sp_attrs.update(
                                trace_id=hop["trace_id"],
                                trace_parent=hop["parent_span_id"],
                                trace_span=hop["span_id"],
                            )
                            loader.last_trace = hop
                    if step != expected:
                        raise P.ProtocolError(
                            f"stripe {i}/{self.count}: out-of-order step "
                            f"{step}, expected {expected}"
                        )
                    observed = observe_wire_lineage(
                        loader.registry, lineage, recv_ns
                    )
                    if observed is not None:
                        loader.last_lineage = observed
                        loader.recent_lineage.append(observed)
                    expected += self.count
                    try:
                        P.send_msg(sock, P.MSG_ACK, {"step": step})
                    except (ConnectionError, OSError):
                        pass  # the next recv sees the drop
                    loader.counters.add("batches_received")
                    t0 = time.perf_counter()
                    self._put(i, (step, batch))
                    loader.counters.add(
                        "recv_backpressure_s", time.perf_counter() - t0
                    )
                elif msg_type == P.MSG_END:
                    self._put(i, _STRIPE_END)
                    return
                elif msg_type == P.MSG_ERROR:
                    raise RuntimeError(
                        f"data server {addr}: {payload.get('message')}"
                    )
                else:
                    raise P.ProtocolError(f"unexpected message {msg_type}")
        except BaseException as exc:  # fatal: surface through the merge loop
            self._put(i, exc)
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _put(self, i: int, item) -> None:
        """Bounded put that a close() can always unblock (the queue is
        drained on teardown, so a blocked pump exits within one timeout)."""
        while not (self.closed.is_set() or self.stop.is_set()):
            try:
                self.queues[i].put(item, timeout=0.25)
                return
            except queue.Full:
                continue

    def next_batch(self, step: int):
        """Blocking pop of ``step`` from its owner stripe. Returns the host
        batch, raises :class:`_StripeFailure` on a member loss, re-raises
        fatal pump errors, and returns ``None`` when the loader closed."""
        q = self.queues[step % self.count]
        while not self.stop.is_set():
            try:
                item = q.get(timeout=0.25)
            except queue.Empty:
                if self.failed.is_set():
                    raise _StripeFailure(
                        self.failed_addr or "?",
                        ConnectionError("stripe connection lost"),
                    )
                continue
            if item is _STRIPE_END:
                # The owner of an unserved step ended early: the server's
                # plan disagrees with ours — fatal, not a failover.
                raise P.ProtocolError(
                    f"stripe ended before step {step} was served"
                )
            if isinstance(item, _StripeFailure):
                raise item
            if isinstance(item, BaseException):
                raise item
            got, batch = item
            if got != step:
                raise P.ProtocolError(
                    f"merge expected step {step}, stripe delivered {got}"
                )
            return batch
        return None

    def close(self) -> None:
        """Tear the round down and RELEASE every buffered-but-unyielded
        batch's pool leases (a failover drops up to
        ``n * stripe_queue_depth`` decoded batches — they must go back to
        the pool, not strand)."""
        self.closed.set()
        for sock in self.socks:
            try:
                # shutdown BEFORE close: a pump blocked in recv holds the
                # last kernel reference, so a bare close() would neither
                # wake it nor send FIN — the same fd-close-vs-blocked-recv
                # trap _ClientSession.close() documents server-side.
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        for q, t in zip(self.queues, self.threads):
            while t.is_alive():
                try:
                    self._release_item(q.get_nowait())
                except queue.Empty:
                    t.join(timeout=0.1)
        for q in self.queues:  # pumps gone: drain the leftovers
            while True:
                try:
                    self._release_item(q.get_nowait())
                except queue.Empty:
                    break

    def _release_item(self, item) -> None:
        if isinstance(item, tuple) and len(item) == 2:
            self.loader._release(item[1])


class FleetLoader:
    """Iterate device-ready batches served by a fleet of data servers.

    Parameters mirror :class:`~..service.client.RemoteLoader` where they
    overlap; ``coordinator_addr`` replaces the single server address.

    Since r16 this class is the runtime engine beneath a
    :class:`~..data.graph.LoaderGraph` assembly (``LanceSource → Decode →
    ... → FleetTransport``) — prefer composing the graph.
    """

    def __init__(
        self,
        coordinator_addr: str,
        batch_size: int,
        process_index: int,
        process_count: int,
        device_put_fn: Optional[Callable[[dict], dict]] = None,
        *,
        sampler_type: str = "batch",
        shuffle: bool = False,
        seed: int = 0,
        epoch: int = 0,
        prefetch: int = 2,
        columns: Optional[Sequence[str]] = None,
        connect_retries: int = 3,
        resolve_retries: int = 10,
        backoff_s: float = 0.2,
        timeout_s: float = 120.0,
        task_type: Optional[str] = None,
        image_size: Optional[int] = None,
        seq_len: Optional[int] = None,
        device_decode: Optional[bool] = None,
        token_pack: Optional[bool] = None,
        dataset_fingerprint: Optional[str] = None,
        job_id: Optional[str] = None,
        job_priority: Optional[str] = None,
        registry: Optional[MetricsRegistry] = None,
        buffer_pool=None,
        stripe_queue_depth: int = 2,
        exclusion_ttl_s: float = 10.0,
    ):
        self.coordinator_host, self.coordinator_port = P.parse_hostport(
            coordinator_addr
        )
        self.batch_size = batch_size
        self.process_index = process_index
        self.process_count = process_count
        self.device_put_fn = device_put_fn
        self.sampler_type = sampler_type
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = epoch
        self.prefetch = max(1, prefetch)
        self.columns = list(columns) if columns is not None else None
        self.connect_retries = max(1, connect_retries)
        self.resolve_retries = max(1, resolve_retries)
        self.backoff_s = backoff_s
        self.timeout_s = timeout_s
        self.task_type = task_type
        self.image_size = image_size
        self.seq_len = seq_len
        self.device_decode = device_decode
        # Ragged token plane (v4+): like striping, packing is not
        # downgrade-safe — every dialed member must speak
        # TOKEN_PACK_MIN_VERSION (checked next to the stripe floor).
        self.token_pack = token_pack
        # Declared dataset identity (see RemoteLoader): every member of
        # the fleet must serve the SAME dataset content — a stale-mirror
        # member is rejected at its handshake, not silently striped in.
        self.dataset_fingerprint = dataset_fingerprint
        # Job plane (v6): declared tenancy, carried on every member HELLO
        # and on RESOLVE (the coordinator's registry learns the job even
        # before any member admits it). An EXPLICIT job_id shares
        # striping's no-downgrade rule — every member must speak
        # JOB_MIN_VERSION (checked next to the stripe floor); None = the
        # implicit default job, fine against any member.
        self.job_id = job_id
        self.job_priority = job_priority
        self.registry = registry if registry is not None else default_registry()
        self.counters = ServiceCounters(prefix="fleet", registry=self.registry)
        self.buffer_pool = buffer_pool
        self.stripe_queue_depth = stripe_queue_depth
        self.exclusion_ttl_s = exclusion_ttl_s
        self.recent_lineage: deque = deque(maxlen=1024)
        self.last_lineage: Optional[dict] = None
        # Last batch's continued trace context (v5), as in RemoteLoader.
        self.last_trace: Optional[dict] = None
        self.client_id = uuid.uuid4().hex
        self.generation: int = 0  # last resolved lease generation
        self._num_steps: Optional[int] = None
        # addr -> monotonic deadline: members excluded from striping after a
        # failure, until the TTL lapses (a recovered server rejoins rounds).
        self._excluded: dict = {}
        # Resume cursor (contract: data/pipeline.py): the merge loop's
        # global cursor starts here — the same mechanism failover restriping
        # uses, so a checkpoint resume IS a restripe from the saved step.
        self._start_step = 0
        self._yielded = 0
        # Autotune surface (tune/): live merge-queue bound + stripe width.
        self._live = _LiveQueues()
        # 0 = stripe over every assigned member (the fixed-knob default,
        # unchanged behavior); >0 caps the round at the first N of THIS
        # process's member slice. Width changes apply at the next round
        # boundary — _restripe asks the orchestrator to end the current
        # round at the cursor, the exact move failover already makes, so
        # the stream stays bit-identical through a re-stripe.
        self.stripe_width = 0
        self._last_round_width = 1
        # This process's assigned membership size at the last round open
        # (pre-cap; 0 = no round yet): the effective-width ceiling a width
        # change is judged against, so growing past live membership never
        # churns a round it cannot change.
        self._last_assigned = 0
        self._restripe = threading.Event()

    def set_prefetch(self, depth: int) -> int:
        """Autotune actuator: the merged-stream prefetch bound, live."""
        depth = max(1, int(depth))
        self.prefetch = depth  # ldt: ignore[LDT1002] -- atomic int swap; readers take any recent value
        self._live.resize_total(depth)
        return depth

    def set_stripe_width(self, width: int) -> int:
        """Autotune actuator: re-stripe the plan over ``width`` members.
        Signals the orchestrator to end the current round at its cursor and
        open a fresh striping — the same cursor-preserving move failover
        makes, so no step is lost, duplicated, or reordered. The effective
        width is capped by live membership at round-open time, and a change
        that cannot alter the effective count (growing past the members
        this process has) records the request WITHOUT churning the round —
        ending a healthy merge early buys nothing."""
        width = max(1, int(width))
        assigned = self._last_assigned
        old = self.stripe_width or assigned or self._last_round_width
        self.stripe_width = width  # ldt: ignore[LDT1002] -- atomic int swap read at round-open
        if assigned:
            old = min(old, assigned)
            width = min(width, assigned)
        if width != old:
            self._restripe.set()
        return self.stripe_width

    def tunables(self):
        """Autotune registration surface (tune/)."""
        return [
            Tunable(
                "prefetch", lambda: self.prefetch, self.set_prefetch,
                lo=1, hi=16,
                doc="merged host batches buffered ahead of the consumer",
            ),
            Tunable(
                "stripe_width",
                lambda: self.stripe_width or self._last_round_width,
                self.set_stripe_width,
                lo=1, hi=32,
                doc="fleet members this shard's plan stripes across",
            ),
        ]

    def state_dict(self) -> dict:
        return {"epoch": int(self.epoch), "step": int(self._yielded)}

    def load_state_dict(self, state: dict) -> None:
        if "epoch" in state:
            self.set_epoch(int(state["epoch"]))
        step = int(state.get("step", 0))
        if step < 0:
            raise ValueError(f"negative resume cursor: {step}")
        # Resume cursor: loaded between iterations, while no receiver
        # thread is live (the checkpoint-restore contract in
        # data/pipeline.py) — happens-before the next __iter__ spawn.
        self._start_step = step  # ldt: ignore[LDT1002] -- set while quiescent, before __iter__ spawns the receiver
        self._yielded = step

    # -- coordinator --------------------------------------------------------

    def _resolve_once(self) -> dict:
        # Re-bracket IPv6 for the shared parser (parse_hostport rejects a
        # bare "::1:port" as ambiguous, by design).
        host = self.coordinator_host
        if ":" in host:
            host = f"[{host}]"
        # Declare the job at resolve time: the registry row (priority,
        # cursor) exists even while no member session is admitted yet.
        return resolve_fleet(
            f"{host}:{self.coordinator_port}", timeout_s=self.timeout_s,
            job_id=self.job_id, job_priority=self.job_priority,
        )

    def _resolve_members(
        self, stop: Optional[threading.Event] = None,
    ) -> list:
        """Membership with retry/backoff (an empty fleet keeps retrying —
        members may still be booting). Returns THIS process's slice of the
        member list sorted by ``server_id`` (:func:`members_for_process` —
        every training host stripes over its own disjoint members, so no
        server ships redundant bytes to two hosts), with recently-failed
        addresses excluded — unless exclusion would empty the slice, in
        which case the exclusions are dropped (a possibly-recovered server
        beats certain starvation)."""
        last: Optional[Exception] = None
        policy = RetryPolicy(
            attempts=self.resolve_retries, base_s=self.backoff_s, cap_s=2.0
        )
        for _attempt in retrying(
            policy, stop=stop, registry=self.registry,
            interrupt_message="loader closed during resolve",
        ):
            try:
                reply = self._resolve_once()
            except (ConnectionError, OSError, P.ProtocolError) as exc:
                last = exc
                self.counters.add("resolve_errors")
                continue
            self.counters.add("resolves")
            self.generation = int(reply.get("generation", 0))
            self.counters.gauge("lease_generation", self.generation)
            members = sorted(
                reply.get("members", []),
                key=lambda m: str(m.get("server_id", "")),
            )
            self.counters.gauge("members", len(members))
            # Slice BEFORE exclusion: the process→member mapping must
            # stay stable across failover rounds (an exclusion on host
            # A must not shift host B's stripes onto new servers).
            mine = members_for_process(
                members, self.process_index, self.process_count
            )
            self.counters.gauge("members_assigned", len(mine))
            now = time.monotonic()
            self._excluded = {
                a: t for a, t in self._excluded.items() if t > now
            }
            live = [
                m for m in mine
                if m.get("addr") not in self._excluded
            ]
            if not live:
                live = mine  # all excluded: try everyone again
            if live:
                return live
            last = ConnectionError("fleet has no registered members")
        raise ConnectionError(
            f"fleet coordinator {self.coordinator_host}:"
            f"{self.coordinator_port}: no usable membership after "
            f"{self.resolve_retries} attempts: {last}"
        ) from last

    # -- data servers -------------------------------------------------------

    def _hello(self, start_step: int, stripe_index: int, stripe_count: int,
               probe: bool = False) -> dict:
        return P.hello(
            batch_size=self.batch_size,
            process_index=self.process_index,
            process_count=self.process_count,
            sampler_type=self.sampler_type,
            shuffle=self.shuffle,
            seed=self.seed,
            epoch=self.epoch,
            start_step=start_step,
            stripe_index=stripe_index,
            stripe_count=stripe_count,
            columns=self.columns,
            client_id=self.client_id,
            probe=probe,
            task_type=self.task_type,
            image_size=self.image_size,
            seq_len=self.seq_len,
            device_decode=self.device_decode,
            token_pack=self.token_pack,
            dataset_fingerprint=self.dataset_fingerprint,
            job_id=self.job_id,
            job_priority=self.job_priority,
        )

    def _dial_member(self, addr: str, start_step: int, stripe_index: int,
                     stripe_count: int, stop: Optional[threading.Event],
                     probe: bool = False):
        """Dial + v3 handshake with one member. ConnectionError after the
        quick per-member retries means *this member* is down (failover
        material); a handshake rejection is fatal — a fleet whose servers
        reject our plan parameters cannot be failed over to."""
        host, port = P.parse_hostport(addr)
        last: Optional[Exception] = None
        policy = RetryPolicy(
            attempts=self.connect_retries, base_s=self.backoff_s, cap_s=2.0
        )
        for _attempt in retrying(
            policy, stop=stop, registry=self.registry,
            interrupt_message="loader closed during connect",
        ):
            try:
                sock = socket.create_connection(
                    (host, port), timeout=min(self.timeout_s, 10.0)
                )
                try:
                    sock.settimeout(self.timeout_s)  # handshake recv bound
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                    1)
                    P.send_msg(sock, P.MSG_HELLO, self._hello(
                        start_step, stripe_index, stripe_count, probe
                    ))
                    msg_type, reply = P.recv_msg(sock)
                    if msg_type == P.MSG_ERROR:
                        raise P.ProtocolError(
                            f"data server {addr} rejected handshake: "
                            f"{reply.get('message', '')}"
                        )
                    if msg_type != P.MSG_HELLO_OK:
                        raise P.ProtocolError(
                            f"expected HELLO_OK, got message type {msg_type}"
                        )
                    # Striping is NOT downgrade-safe: a pre-v3 server would
                    # ignore the stripe fields and serve EVERY step — silent
                    # duplication across the fleet. Unlike RemoteLoader there
                    # is no version-downgrade retry here, by design.
                    if int(reply.get("version", 0)) < P.STRIPE_MIN_VERSION:
                        raise P.ProtocolError(
                            f"data server {addr} speaks protocol "
                            f"{reply.get('version')} < "
                            f"{P.STRIPE_MIN_VERSION} "
                            "(no stripe support) — upgrade it before "
                            "fleeting"
                        )
                    # Packing shares striping's no-downgrade rule: a
                    # member that cannot speak the ragged plane would
                    # silently stripe PADDED rows into a packed stream.
                    if self.token_pack and int(
                        reply.get("version", 0)
                    ) < P.TOKEN_PACK_MIN_VERSION:
                        raise P.ProtocolError(
                            f"data server {addr} speaks protocol "
                            f"{reply.get('version')} < "
                            f"{P.TOKEN_PACK_MIN_VERSION} (no token_pack "
                            "support) — upgrade it or train with "
                            "--no_token_pack"
                        )
                    # An explicit job shares the same no-downgrade rule: a
                    # pre-v6 member would drop the job fields and stripe
                    # this stream under the anonymous default tenant — no
                    # per-job cursor, fairness or admission — while the
                    # trainer believes its job_id took effect fleet-wide.
                    if self.job_id is not None and int(
                        reply.get("version", 0)
                    ) < P.JOB_MIN_VERSION:
                        raise P.ProtocolError(
                            f"data server {addr} speaks protocol "
                            f"{reply.get('version')} < "
                            f"{P.JOB_MIN_VERSION} (no job plane) — "
                            "upgrade it or drop the explicit job_id "
                            f"{self.job_id!r}"
                        )
                    # Stripe-echo check: the HELLO_OK carries back the
                    # residue class the server will actually serve. A
                    # server that accepted the handshake but mis-parsed,
                    # DROPPED, or ignored the stripe fields would stream
                    # the wrong class — duplicated steps on one stripe,
                    # holes on another — with every frame individually
                    # valid. The echo is REQUIRED (every v3 server has
                    # sent it since striping existed): defaulting a
                    # missing echo to the requested values would pass the
                    # exact server this check exists to catch. Fatal like
                    # the version floor above: a fleet serving wrong
                    # residue classes cannot be failed over to.
                    echoed = (
                        reply.get("stripe_index"),
                        reply.get("stripe_count"),
                    )
                    if not all(
                        P.is_json_int(e) and e == want
                        for e, want in zip(
                            echoed, (stripe_index, stripe_count)
                        )
                    ):
                        raise P.ProtocolError(
                            f"data server {addr} echoed stripe "
                            f"{echoed[0]!r}/{echoed[1]!r}, requested "
                            f"{stripe_index}/{stripe_count} — it would "
                            "serve the wrong residue class"
                        )
                    # Job-echo check (the RemoteLoader posture): a v6
                    # member echoes the admitted job_id; disagreement
                    # means this stripe was filed under another tenant.
                    if self.job_id is not None and "job_id" in reply \
                            and reply.get("job_id") != self.job_id:
                        raise P.ProtocolError(
                            f"data server {addr} echoed job_id "
                            f"{reply.get('job_id')!r}, declared "
                            f"{self.job_id!r} — tenancy desync"
                        )
                    self._num_steps = int(reply["num_steps"])  # ldt: ignore[LDT1002] -- idempotent plan-length cache: every writer stores the same value for a given epoch
                    sock.settimeout(None)  # streaming: no recv deadline
                    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE,
                                    1)
                    return sock
                except BaseException:
                    # EVERY failure after the dial closes the socket here —
                    # the previous typed handlers (ProtocolError,
                    # ConnectionError/OSError) let a malformed reply
                    # (KeyError/ValueError) escape with the fd open
                    # (LDT1201's exception-edge leak).
                    sock.close()
                    raise
            except (ConnectionError, OSError) as exc:
                last = exc
                self.counters.add("connect_retries")
        raise ConnectionError(
            f"data server {addr} unreachable after "
            f"{self.connect_retries} attempts: {last}"
        ) from last

    # -- plan metadata ------------------------------------------------------

    def __len__(self) -> int:
        """Step count of this shard's plan (probe handshake against any
        live member, cached)."""
        if self._num_steps is None:
            members = self._resolve_members()
            last: Optional[Exception] = None
            for m in members:
                try:
                    sock = self._dial_member(
                        m["addr"], 0, 0, 1, None, probe=True
                    )
                    sock.close()
                    break
                except (ConnectionError, OSError) as exc:
                    last = exc
            else:
                raise ConnectionError(
                    f"no fleet member reachable for probe: {last}"
                ) from last
        return int(self._num_steps)

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle parity with ``RemoteLoader.set_epoch``."""
        if epoch != self.epoch:
            # Epoch rollover runs between epochs, while no receiver
            # thread is live — happens-before the next __iter__ spawn.
            self.epoch = epoch  # ldt: ignore[LDT1002] -- set while quiescent, before __iter__ spawns the receiver
            self._num_steps = None  # ldt: ignore[LDT1002] -- set while quiescent, before __iter__ spawns the receiver
            # A new epoch's plan starts at its own step 0.
            self._start_step = 0  # ldt: ignore[LDT1002] -- set while quiescent, before __iter__ spawns the receiver
            self._yielded = 0

    def _release(self, batch) -> None:
        if self.buffer_pool is not None:
            self.buffer_pool.release_batch(batch)

    # -- iteration ----------------------------------------------------------

    def _receive(self, q: "queue.Queue", stop: threading.Event) -> None:
        """Orchestrator thread: stripe rounds → merged plan-order stream
        into the bounded queue, restriping from the cursor on member loss."""
        # First step not yet handed to the consumer. Starts at the loaded
        # checkpoint cursor: resume after a trainer restart is the same
        # restripe-from-cursor move failover already makes mid-run.
        cursor = self._start_step
        try:
            if self._num_steps is None:
                self.__len__()  # probe via any member (retries inside)
            num_steps = int(self._num_steps)
            while cursor < num_steps and not stop.is_set():
                members = self._resolve_members(stop)
                # Autotune stripe width: cap the round at the first N of
                # this process's slice (0 = all, the fixed-knob default).
                # Clearing the restripe flag here (not when it is noticed)
                # makes a width change that lands mid-round-open coalesce
                # into the round it is about to shape.
                self._restripe.clear()
                self._last_assigned = len(members)  # ldt: ignore[LDT1002] -- advisory ceiling for set_stripe_width; torn reads impossible for an int
                width = self.stripe_width
                if width and width < len(members):
                    members = members[:width]
                self._last_round_width = len(members)  # ldt: ignore[LDT1002] -- advisory gauge for the tunable getter; torn reads impossible for an int
                t0 = time.perf_counter()
                rnd = _StripeRound(self, members, cursor, stop)
                try:
                    rnd.connect()
                except _StripeFailure as f:
                    self._failover(f, cursor)
                    continue
                self.counters.gauge("stripes", rnd.count)
                if cursor > self._start_step:
                    # Failover restripe cost, dial-to-streaming. The initial
                    # stripe setup is not a REbalance and stays out.
                    self.counters.observe(
                        "rebalance_ms", (time.perf_counter() - t0) * 1e3
                    )
                try:
                    while cursor < num_steps and not stop.is_set():
                        if self._restripe.is_set():
                            # Width change: end this round at the cursor —
                            # the outer loop re-resolves and re-stripes from
                            # exactly here (failover's move, minus the
                            # exclusion), so the merged stream is unbroken.
                            self.counters.add("restripes")
                            break
                        batch = rnd.next_batch(cursor)
                        if batch is None:  # loader closed
                            return
                        q.put(batch)
                        cursor += 1
                except _StripeFailure as f:
                    self._failover(f, cursor)
                    continue  # the finally below tears the round down
                finally:
                    rnd.close()
            if cursor >= num_steps:
                q.put(_SENTINEL)
        except BaseException as exc:  # surface to the consumer
            q.put(exc)

    def _failover(self, failure: _StripeFailure, cursor: int) -> None:
        """A member was lost: exclude its address for a TTL (the next
        resolve stripes over the survivors) and count the event."""
        self._excluded[failure.addr] = (
            time.monotonic() + self.exclusion_ttl_s
        )
        self.counters.add("failovers_total")
        self.counters.gauge("resume_cursor", cursor)

    def __iter__(self) -> Iterator[dict]:
        q: "queue.Queue" = AdjustableQueue(self.prefetch)
        self._live.install([q])
        stop = threading.Event()
        receiver = threading.Thread(
            target=self._receive, args=(q, stop), daemon=True,
            name="ldt-fleet-loader",
        )
        receiver.start()
        self._yielded = self._start_step
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                # Consumer blocked on an empty queue: the fleet (wire or
                # decode) is the bottleneck — attributable via
                # StepTimer.attach_counters, same as RemoteLoader.
                self.counters.add("client_stall_s", time.perf_counter() - t0)
                if item is _SENTINEL:
                    return
                if isinstance(item, BaseException):
                    raise item
                self._yielded += 1
                host = item
                if self.device_put_fn is not None:
                    item = self.device_put_fn(host)
                    self._release(host)
                    host = None
                yield item
                if host is not None:
                    self._release(host)
        finally:
            stop.set()
            self._live.clear()
            while receiver.is_alive():
                try:
                    # Drained items are undelivered host batches — return
                    # their pool leases on the way out.
                    drained = q.get_nowait()
                    if not (drained is _SENTINEL
                            or isinstance(drained, BaseException)):
                        self._release(drained)
                except queue.Empty:
                    receiver.join(timeout=0.1)
