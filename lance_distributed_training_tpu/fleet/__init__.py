"""Elastic data-plane fleet — N data servers behind one coordinator.

One :class:`~..service.server.DataService` is both a decode-throughput
ceiling and a single point of failure. This package turns the single-server
plane into a *fleet* (the tf.data-service dispatcher/worker shape,
PAPERS.md):

* :mod:`.coordinator` — :class:`Coordinator`: the control plane. Tracks
  data-server membership (registration + heartbeats) and hands out
  generation-numbered **shard leases** — each live member owns a disjoint
  slice of the global fragment space, recomputed on every join / leave /
  heartbeat expiry.
* :mod:`.agent` — :class:`FleetAgent`: the server-side half. Registers a
  ``DataService`` on start, heartbeats on a daemon thread, surfaces lease
  changes back to the service (which re-plans), deregisters on stop.
* :mod:`.balancer` — :class:`FleetLoader`: the client. Discovers endpoints
  from the coordinator, stripes its shard's plan across live servers
  (protocol-v3 ``stripe_index/stripe_count`` HELLOs), and on server loss
  re-resolves membership and re-stripes from the exact resume cursor —
  preserving the no-loss / no-duplication batch-sequence contract
  ``RemoteLoader`` guarantees against one server.
* :mod:`.chaos` — deterministic fault injection (scripted kill / stall /
  partition of member servers) so failover is *tested*, not asserted.
* :mod:`.jobs` — the multi-tenant job plane (protocol v6):
  :class:`JobPlane` on each server (per-job admission, weighted-fair
  stride scheduling of produce capacity, per-job counters/cursors/SLO
  burn) and :class:`JobRegistry` on the coordinator (fleet-wide per-job
  rows aggregated from member heartbeats — ``ldt jobs``).

Everything rides the existing length-prefixed frame protocol
(:mod:`..service.protocol`); fleet metrics (``fleet_members``,
``fleet_lease_generation``, ``fleet_failovers_total``,
``fleet_rebalance_ms``) land on the same ``/metrics`` + ``/healthz``
surfaces as the rest of the stack. See README "Fleet".
"""

from .balancer import FleetLoader  # noqa: F401
from .coordinator import Coordinator, CoordinatorConfig, serve_coordinator  # noqa: F401
from .jobs import (  # noqa: F401
    AdmissionRefused,
    FairScheduler,
    JobPlane,
    JobRegistry,
    PriorityClass,
    PRIORITY_CLASSES,
)

__all__ = [
    "AdmissionRefused",
    "Coordinator",
    "CoordinatorConfig",
    "FairScheduler",
    "FleetLoader",
    "JobPlane",
    "JobRegistry",
    "PriorityClass",
    "PRIORITY_CLASSES",
    "serve_coordinator",
]
