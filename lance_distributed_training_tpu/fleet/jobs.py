"""Job plane — multi-tenant registry, fairness, and admission control.

One fleet, N logical consumers. Before r20 every HELLO was anonymous:
one cursor per connection, one metric scope per server, and a
Coordinator that balanced *bytes* with no notion of *whose* bytes. This
module is the tf.data-service half the ROADMAP calls out (PAPERS.md
2210.14826 — disaggregated input processing shared across jobs): a
**job** is a named tenant (``job_id`` in the v6 HELLO, see
``service/protocol.py``) with a priority class, its own resume cursors,
its own metric scope, and an admission verdict.

Three pieces, one per plane:

* :class:`FairScheduler` — stride scheduling over *produce* steps. Each
  job owns a virtual pass that advances by ``1/weight`` per granted
  step; when several jobs' producers contend, the lowest pass goes
  first, so long-run produce share converges to the weight ratio.
  Preempting classes (``inference``) sort ahead of every non-preempting
  waiter regardless of pass — a single-batch fetch never queues behind
  a bulk scan. The decision core (:meth:`FairScheduler.pick` /
  :meth:`FairScheduler.advance`) is pure state, unit-testable without
  threads; :meth:`FairScheduler.begin_step` is the blocking wrapper the
  server's producer calls, and its wait is hard-bounded — fairness is
  *pacing*, never a wedge (a dead peer cannot stall another tenant's
  stream, and batch CONTENT is untouched either way — LDT1301: this
  class only decides *when* a step is produced, never *what*).
* :class:`JobPlane` — the DataService-side tenant table. Resolves the
  HELLO's job fields (absent → the implicit default job, which is how
  every pre-v6 peer keeps its exact pre-r20 behavior), admits or
  refuses sessions (:class:`AdmissionRefused` messages start with the
  frozen ``ADMISSION_REFUSED_MARKER`` wire prose), and owns per-job
  ``ServiceCounters`` scopes (``svc_job_<slug>_*`` — the label-less
  registry's name-prefix discipline, LDT601) plus a per-job
  :class:`~..obs.slo.SLOTracker` publishing ``slo_job_<slug>_stall_pct``
  burn-down. Already-admitted jobs are NEVER refused: a failover
  reconnect must always succeed, so admission gates apply to *new*
  tenants only.
* :class:`JobRegistry` — the Coordinator-side fleet view. Aggregates
  the per-job stats that ride member heartbeats (the optional ``jobs``
  field — old coordinators ignore it, exactly like ``queue_wait_hist``)
  into fleet-wide rows (sessions summed, cursors maxed, cache hit
  rates, worst SLO burn) served to ``MSG_FLEET_RESOLVE`` clients,
  ``/healthz``, and the ``ldt jobs`` / ``ldt fleet recommend`` CLIs.
  Cursors survive member loss: the registry keeps the max step it ever
  saw per job, so "where was my job?" has an answer even while the
  fleet that served it is being replaced.

Per-job *plans* need no new machinery: ``plan_for`` keys plans by the
full sampler config and builds them through ``LanceSource.shard_plans``
(the PR-16 graph seam), so two jobs with identical configs share one
plan object and two jobs with different configs cannot drift — and the
PR-13 content-keyed batch cache makes the second same-config job stream
decode-free for free (cross-job cache hits are just cache hits).

Clock policy: admission and stall windows use ``time.monotonic()``
(durations); nothing here touches batch bytes, plan order, or cursor
*computation* — cursors are observed ACKs, recorded as telemetry.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
import re
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from ..obs.registry import MetricsRegistry, default_registry
from ..obs.slo import SLOTracker, scoped_slos
from ..service import protocol as P
from ..utils.metrics import ServiceCounters

__all__ = [
    "DEFAULT_JOB_ID",
    "DEFAULT_PRIORITY",
    "PriorityClass",
    "PRIORITY_CLASSES",
    "job_slug",
    "AdmissionRefused",
    "FairScheduler",
    "JobPlane",
    "JobRegistry",
]

# The implicit tenant: what a v5 peer, or a v6 peer that declared
# nothing, maps onto. Its existence is what makes the job plane
# downgrade-SAFE — pre-r20 exchanges become "the default job" with no
# behavior change, not an error.
DEFAULT_JOB_ID = "default"
DEFAULT_PRIORITY = "training"


@dataclasses.dataclass(frozen=True)
class PriorityClass:
    """One admission/fairness class a job declares in its HELLO.

    ``weight`` is the stride-scheduling share (2:1 weights → 2:1
    long-run produce steps under contention). ``preempt`` classes sort
    ahead of every non-preempting waiter regardless of accumulated
    pass — the low-latency guarantee. ``read_only`` classes are serving
    probes (single-batch fetches, no training epoch) and are exempt
    from the ``admission_max_jobs`` cap: the cap protects bulk decode
    capacity, which a read-only fetch barely touches."""

    name: str
    weight: float
    preempt: bool = False
    read_only: bool = False


# The built-in vocabulary. Unknown classes are refused at admission
# (a typo'd class silently scheduled at some default weight would be
# the skew-class bug this repo refuses everywhere else).
PRIORITY_CLASSES: Dict[str, PriorityClass] = {
    "inference": PriorityClass(
        "inference", weight=4.0, preempt=True, read_only=True
    ),
    "training": PriorityClass("training", weight=2.0),
    "bulk": PriorityClass("bulk", weight=1.0),
}

_SLUG_RE = re.compile(r"[^a-z0-9_]+")


def job_slug(job_id: str) -> str:
    """``job_id`` → metric-safe scope fragment (``[a-z0-9_]+``).

    Registry names must match ``^[a-z][a-z0-9_]*$`` (LDT601); a job id
    is operator prose (``smoke-train``, ``Tenant.A``). Lowercase, map
    every illegal run to ``_``, and never return empty — the result is
    embedded as ``svc_job_<slug>_*`` / ``slo_job_<slug>_*``. Lossy by
    design: colliding tenants are disambiguated by :class:`JobPlane`
    with a content-hash suffix, not here."""
    slug = _SLUG_RE.sub("_", str(job_id).lower()).strip("_")
    return slug or "job"


class AdmissionRefused(Exception):
    """A session's job was refused admission. ``str(exc)`` is the full
    diagnosable message (starts with ``ADMISSION_REFUSED_MARKER``) and
    is what the server sends as the MSG_ERROR payload."""


def _refusal(reason: str) -> AdmissionRefused:
    return AdmissionRefused(f"{P.ADMISSION_REFUSED_MARKER}: {reason}")


class FairScheduler:
    """Weighted-fair stride scheduling of produce steps across jobs.

    State is three maps under one condition variable: per-job virtual
    pass, weight, and preempt flag, plus a count of producer threads
    currently *waiting* per job. Only waiting jobs contend — a job
    whose producer is blocked on its own full queue (a slow consumer)
    neither holds anyone back nor banks credit it would later burst.

    The decision core is pure: :meth:`pick` says which contender goes
    next (``(not preempt, pass, job_id)`` — preemptors first, then
    lowest pass, id as the deterministic tie-break) and :meth:`advance`
    charges one step at ``1/weight``. :meth:`begin_step` wraps them
    with a bounded wait: ``max_wait_s`` caps any single step's fairness
    delay so a wedged tenant degrades fairness, never liveness."""

    def __init__(
        self,
        classes: Optional[Dict[str, PriorityClass]] = None,
        max_wait_s: float = 1.0,
    ):
        self._classes = dict(classes or PRIORITY_CLASSES)
        self.max_wait_s = float(max_wait_s)
        self._cond = threading.Condition()
        self._vpass: Dict[str, float] = {}
        self._weight: Dict[str, float] = {}
        self._preempt: Dict[str, bool] = {}
        self._waiting: Dict[str, int] = {}

    def _ensure_locked(self, job_id: str, priority: str) -> None:
        if job_id in self._vpass:
            return
        cls = self._classes.get(priority) or self._classes.get(
            DEFAULT_PRIORITY, PriorityClass(DEFAULT_PRIORITY, 1.0)
        )
        # Join at the minimum live pass: no catch-up burst (joining at
        # 0 while incumbents sit at 50 would grant 50 back-to-back
        # steps) and no starvation (joining above everyone would).
        self._vpass[job_id] = min(self._vpass.values(), default=0.0)
        self._weight[job_id] = max(1e-6, float(cls.weight))
        self._preempt[job_id] = bool(cls.preempt)

    def ensure(self, job_id: str, priority: str = DEFAULT_PRIORITY) -> None:
        """Register a job's class before its first step (idempotent)."""
        with self._cond:
            self._ensure_locked(job_id, priority)

    def forget(self, job_id: str) -> None:
        with self._cond:
            self._vpass.pop(job_id, None)
            self._weight.pop(job_id, None)
            self._preempt.pop(job_id, None)
            self._cond.notify_all()

    def _pick_locked(self, waiting: Iterable[str]) -> Optional[str]:
        best: Optional[Tuple[Tuple[bool, float, str], str]] = None
        for job_id in waiting:
            self._ensure_locked(job_id, DEFAULT_PRIORITY)
            key = (not self._preempt[job_id], self._vpass[job_id], job_id)
            if best is None or key < best[0]:
                best = (key, job_id)
        return best[1] if best is not None else None

    def pick(self, waiting: Iterable[str]) -> Optional[str]:
        """Which of the contending jobs produces next (pure decision)."""
        with self._cond:
            return self._pick_locked(list(waiting))

    def _advance_locked(self, job_id: str) -> None:
        self._ensure_locked(job_id, DEFAULT_PRIORITY)
        self._vpass[job_id] += 1.0 / self._weight[job_id]
        self._cond.notify_all()

    def advance(self, job_id: str) -> None:
        """Charge ``job_id`` one produce step (pure state update)."""
        with self._cond:
            self._advance_locked(job_id)

    def begin_step(self, job_id: str) -> None:
        """Block (bounded) until it is ``job_id``'s turn, then charge it.

        Fast path — no other job has a waiting producer — takes the
        lock once and returns. Same-job producer threads never pace
        each other (fairness is across tenants, not within one)."""
        deadline = time.monotonic() + self.max_wait_s
        with self._cond:
            self._ensure_locked(job_id, DEFAULT_PRIORITY)
            self._waiting[job_id] = self._waiting.get(job_id, 0) + 1
            try:
                while True:
                    contenders = [j for j, c in self._waiting.items() if c > 0]
                    if len(contenders) <= 1:
                        break
                    if self._pick_locked(contenders) == job_id:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0.0:
                        break  # bounded: degrade fairness, never liveness
                    self._cond.wait(timeout=min(0.05, remaining))
            finally:
                count = self._waiting.get(job_id, 1) - 1
                if count > 0:
                    self._waiting[job_id] = count
                else:
                    self._waiting.pop(job_id, None)
            self._advance_locked(job_id)


class _JobState:
    """One admitted tenant on one DataService (plane-lock protected)."""

    __slots__ = (
        "job_id",
        "priority",
        "slug",
        "counters",
        "sessions",
        "cursors",
        "plan_keys",
        "slo",
    )

    def __init__(
        self,
        job_id: str,
        priority: PriorityClass,
        slug: str,
        counters: ServiceCounters,
        slo: Optional[SLOTracker],
    ):
        self.job_id = job_id
        self.priority = priority
        self.slug = slug
        self.counters = counters
        self.sessions: Set[str] = set()
        self.cursors: Dict[str, int] = {}  # client_id -> last acked step
        self.plan_keys: Set[str] = set()
        self.slo = slo

    def cursor(self) -> int:
        """Max acked step across this job's clients (-1 = none yet)."""
        return max(self.cursors.values(), default=-1)


class JobPlane:
    """The DataService-side tenant table: admission, scopes, cursors.

    ``max_jobs``/``max_stall_pct`` are the admission knobs (``0`` =
    disabled, the default — so a pre-r20 deployment admits everything,
    exactly as before). ``stall_fn`` is the service's windowed stall
    probe; a *new* job arriving while the fleet already burns its stall
    SLO is refused with a diagnosable marker message rather than
    admitted into a brown-out. ``counters`` is the service-wide
    ``svc_`` scope (refusal counter, ``svc_jobs_active`` gauge);
    per-job scopes are created here on first admit."""

    def __init__(
        self,
        *,
        counters: Optional[ServiceCounters] = None,
        registry: Optional[MetricsRegistry] = None,
        max_jobs: int = 0,
        max_stall_pct: float = 0.0,
        stall_fn: Optional[Callable[[], float]] = None,
        slo_interval_s: float = 5.0,
        classes: Optional[Dict[str, PriorityClass]] = None,
    ):
        self._registry = (
            registry if registry is not None else default_registry()
        )
        self._counters = (
            counters
            if counters is not None
            else ServiceCounters(registry=self._registry)
        )
        self._classes = dict(classes or PRIORITY_CLASSES)
        self.max_jobs = int(max_jobs)
        self.max_stall_pct = float(max_stall_pct)
        self._stall_fn = stall_fn
        self._slo_interval_s = float(slo_interval_s)
        self.scheduler = FairScheduler(self._classes)
        self._lock = threading.RLock()
        self._jobs: Dict[str, _JobState] = {}
        self._slugs: Dict[str, str] = {}  # slug -> owning job_id
        # Per-job stall windows: job_id -> (monotonic instant,
        # queue_empty_s total at that instant); consumed by the per-job
        # SLO probe, which runs on the tracker ticker.
        self._stall_prev: Dict[str, Tuple[float, float]] = {}

    # -- HELLO resolution --------------------------------------------------

    @staticmethod
    def resolve(job_id, priority) -> Tuple[str, str]:
        """Raw HELLO ``job_id``/``job_priority`` fields → ``(job_id,
        priority)`` with the implicit default for absent/null values (v5
        peers, undeclared v6). Takes the fields, not the payload, so the
        server's handshake reads them where LDT1401 can see the pairing."""
        return (
            str(job_id) if job_id else DEFAULT_JOB_ID,
            str(priority) if priority else DEFAULT_PRIORITY,
        )

    # -- admission ---------------------------------------------------------

    def _slug_locked(self, job_id: str) -> str:
        slug = job_slug(job_id)
        owner = self._slugs.get(slug)
        if owner is not None and owner != job_id:
            # Colliding tenants ("a-b" vs "a.b" both → "a_b"): the
            # second comer gets a content-hash suffix so its metric
            # scope stays distinct and deterministic for this pair.
            digest = hashlib.sha1(job_id.encode("utf-8")).hexdigest()[:6]
            slug = f"{slug}_{digest}"
        self._slugs[slug] = job_id
        return slug

    def admit(self, job_id: str, priority: str, session_key: str) -> None:
        """Admit one session of ``job_id`` or raise AdmissionRefused.

        Gates apply to NEW jobs only — an already-admitted job's
        reconnect (failover, resume, a second worker process) must
        always succeed, or a fleet blip would strand a tenant that was
        already serving. A re-declaration with a *different* priority
        class is refused as skew: two halves of one job scheduled at
        different weights would silently break the fair-share story."""
        with self._lock:
            cls = self._classes.get(priority)
            if cls is None:
                self._counters.add("admission_refusals")
                raise _refusal(
                    f"unknown priority class {priority!r} for job "
                    f"{job_id!r} (known: {sorted(self._classes)})"
                )
            state = self._jobs.get(job_id)
            if state is not None:
                if state.priority.name != priority:
                    self._counters.add("admission_refusals")
                    raise _refusal(
                        f"job {job_id!r} already admitted with priority "
                        f"class {state.priority.name!r}, HELLO declares "
                        f"{priority!r} — priority skew across one job's "
                        f"clients"
                    )
                state.sessions.add(session_key)
                self._publish_locked(state)
                return
            if self.max_jobs > 0 and not cls.read_only:
                active = sum(
                    1
                    for s in self._jobs.values()
                    if not s.priority.read_only
                )
                if active >= self.max_jobs:
                    self._counters.add("admission_refusals")
                    raise _refusal(
                        f"job capacity reached ({active}/{self.max_jobs} "
                        f"non-read-only jobs admitted); job {job_id!r} "
                        f"must wait for a slot (--admission_max_jobs)"
                    )
            if self.max_stall_pct > 0.0 and self._stall_fn is not None:
                try:
                    stall = float(self._stall_fn())
                except Exception:  # noqa: BLE001 — a broken probe must
                    stall = 0.0  # not close the admission gate
                if stall > self.max_stall_pct:
                    self._counters.add("admission_refusals")
                    raise _refusal(
                        f"fleet stall {stall:.1f}% exceeds the admission "
                        f"ceiling {self.max_stall_pct:.1f}% "
                        f"(--admission_max_stall_pct); admitting new job "
                        f"{job_id!r} would breach the stall SLO for "
                        f"every admitted tenant"
                    )
            slug = self._slug_locked(job_id)
            counters = ServiceCounters(
                prefix=f"svc_job_{slug}", registry=self._registry
            )
            slo = SLOTracker(
                probes={
                    f"job_{slug}_stall_pct": (
                        lambda j=job_id: self._job_stall(j)
                    )
                },
                slos=scoped_slos(f"job_{slug}"),
                registry=self._registry,
                interval_s=self._slo_interval_s,
            ).start()
            state = _JobState(job_id, cls, slug, counters, slo)
            state.sessions.add(session_key)
            self._jobs[job_id] = state
            self.scheduler.ensure(job_id, priority)
            self._publish_locked(state)

    def release(self, job_id: str, session_key: str) -> None:
        """One session ended. The job's state (cursor, scope, class)
        survives — reconnects resume the same tenant."""
        with self._lock:
            state = self._jobs.get(job_id)
            if state is None:
                return
            state.sessions.discard(session_key)
            self._publish_locked(state)

    def _publish_locked(self, state: _JobState) -> None:
        state.counters.gauge("sessions", float(len(state.sessions)))
        state.counters.gauge("cursor", float(state.cursor()))
        self._counters.gauge("jobs_active", float(len(self._jobs)))

    # -- per-job accounting (called from the session hot paths) ------------

    def counters_for(self, job_id: str) -> Optional[ServiceCounters]:
        with self._lock:
            state = self._jobs.get(job_id)
            return state.counters if state is not None else None

    def note_cursor(self, job_id: str, client_id: str, step: int) -> None:
        """Record an observed ACK — the per-job resume cursor view."""
        with self._lock:
            state = self._jobs.get(job_id)
            if state is None:
                return
            prev = state.cursors.get(client_id, -1)
            if step > prev:
                state.cursors[client_id] = int(step)
                state.counters.gauge("cursor", float(state.cursor()))

    def note_plan(self, job_id: str, plan_key) -> None:
        """Record which shared plan instance this job streams from."""
        with self._lock:
            state = self._jobs.get(job_id)
            if state is not None and len(state.plan_keys) < 32:
                state.plan_keys.add(str(plan_key))

    def note_cache(self, job_id: str, hit: bool) -> None:
        with self._lock:
            state = self._jobs.get(job_id)
        if state is not None:
            state.counters.add("cache_hit" if hit else "cache_miss")

    def begin_step(self, job_id: str) -> None:
        self.scheduler.begin_step(job_id)

    # -- per-job SLO probe -------------------------------------------------

    def _job_stall(self, job_id: str) -> float:
        """Windowed per-job stall % (share of the window this job's
        senders sat on an empty queue, per session), NaN until two
        samples exist. Mirrors ``DataService.pressure`` at job scope."""
        with self._lock:
            state = self._jobs.get(job_id)
            if state is None:
                return math.nan
            snap = state.counters.snapshot()
            empty = float(snap.get(f"svc_job_{state.slug}_queue_empty_s", 0.0))
            sessions = max(1, len(state.sessions))
            now = time.monotonic()
            prev = self._stall_prev.get(job_id)
            self._stall_prev[job_id] = (now, empty)
        if prev is None:
            return math.nan
        window = now - prev[0]
        if window <= 0.0:
            return math.nan
        return min(100.0, 100.0 * (empty - prev[1]) / (window * sessions))

    # -- observability -----------------------------------------------------

    def stats(self) -> Dict[str, dict]:
        """Per-job stats for heartbeats / ``/healthz`` — JSON-safe,
        objective names de-scoped back to their base (``stall_pct``)
        so consumers need not know the slug."""
        with self._lock:
            states = list(self._jobs.values())
        out: Dict[str, dict] = {}
        for state in states:
            snap = state.counters.snapshot()
            prefix = f"svc_job_{state.slug}_"
            slo_status = {}
            if state.slo is not None:
                scope = f"job_{state.slug}_"
                for name, entry in state.slo.status().items():
                    base = (
                        name[len(scope):] if name.startswith(scope) else name
                    )
                    slo_status[base] = entry
            out[state.job_id] = {
                "priority": state.priority.name,
                "sessions": len(state.sessions),
                "cursor": state.cursor(),
                "plans": sorted(state.plan_keys),
                "batches_sent": snap.get(prefix + "batches_sent", 0.0),
                "cache_hit": snap.get(prefix + "cache_hit", 0.0),
                "cache_miss": snap.get(prefix + "cache_miss", 0.0),
                "slo": slo_status,
            }
        return out

    def stop(self) -> None:
        with self._lock:
            states = list(self._jobs.values())
        for state in states:
            if state.slo is not None:
                state.slo.stop()


def _hit_rate(hit: float, miss: float) -> Optional[float]:
    total = hit + miss
    return round(hit / total, 4) if total > 0 else None


class JobRegistry:
    """The Coordinator-side fleet-wide job view.

    Fed from two directions: ``MSG_FLEET_RESOLVE`` payloads *declare* a
    job before any member has served it (so ``ldt jobs`` can see a
    tenant the moment its loader resolves), and member heartbeats carry
    each DataService's :meth:`JobPlane.stats` (the optional ``jobs``
    field — ignored by old coordinators, like every heartbeat extension
    since v5). Cursors are retained at registry scope beyond member
    loss: the max acked step per job survives the very failover that
    destroyed the member-side state."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._declared: Dict[str, str] = {}  # job_id -> priority class
        self._members: Dict[str, Dict[str, dict]] = {}
        self._cursors: Dict[str, int] = {}  # job_id -> max step ever seen

    def declare(self, job_id, priority=None) -> None:
        """A resolving client announced its job (additive, idempotent)."""
        if not job_id or not isinstance(job_id, str):
            return
        with self._lock:
            if isinstance(priority, str) and priority:
                self._declared[job_id] = priority
            else:
                self._declared.setdefault(job_id, DEFAULT_PRIORITY)

    def observe_member(self, server_id: str, jobs) -> None:
        """Absorb one heartbeat's per-job stats (malformed → ignored:
        telemetry must never kill the heartbeat handler)."""
        if not isinstance(jobs, dict):
            return
        clean: Dict[str, dict] = {}
        for job_id, entry in jobs.items():
            if not isinstance(job_id, str) or not isinstance(entry, dict):
                continue
            clean[job_id] = entry
        with self._lock:
            self._members[server_id] = clean
            for job_id, entry in clean.items():
                self._declared.setdefault(
                    job_id, str(entry.get("priority") or DEFAULT_PRIORITY)
                )
                cursor = entry.get("cursor")
                if P.is_json_int(cursor):
                    prev = self._cursors.get(job_id, -1)
                    if cursor > prev:
                        self._cursors[job_id] = cursor

    def drop_member(self, server_id: str) -> None:
        """Member expired or deregistered — its live stats leave the
        aggregate; registry-scope cursors stay."""
        with self._lock:
            self._members.pop(server_id, None)

    def payload(self) -> List[dict]:
        """Fleet-wide per-job rows (JSON-safe, sorted by job_id) for
        RESOLVE_OK / ``/healthz`` / ``ldt jobs``."""
        with self._lock:
            rows: Dict[str, dict] = {}
            for job_id, priority in self._declared.items():
                rows[job_id] = {
                    "job_id": job_id,
                    "priority": priority,
                    "sessions": 0,
                    "cursor": self._cursors.get(job_id, -1),
                    "batches_sent": 0.0,
                    "cache_hit": 0.0,
                    "cache_miss": 0.0,
                    "slo_burn": {},
                }
            for member_jobs in self._members.values():
                for job_id, entry in member_jobs.items():
                    row = rows.get(job_id)
                    if row is None:
                        continue
                    pr = entry.get("priority")
                    if isinstance(pr, str) and pr:
                        row["priority"] = pr
                    sessions = entry.get("sessions")
                    if P.is_json_int(sessions):
                        row["sessions"] += sessions
                    for key in ("batches_sent", "cache_hit", "cache_miss"):
                        value = entry.get(key)
                        if isinstance(value, (int, float)) and not isinstance(
                            value, bool
                        ):
                            row[key] += float(value)
                    slo = entry.get("slo")
                    if isinstance(slo, dict):
                        for objective, detail in slo.items():
                            burn = (
                                detail.get("burn")
                                if isinstance(detail, dict)
                                else None
                            )
                            if not isinstance(burn, dict):
                                continue
                            worst = row["slo_burn"].setdefault(objective, {})
                            for label, rate in burn.items():
                                if isinstance(
                                    rate, (int, float)
                                ) and not isinstance(rate, bool):
                                    worst[label] = max(
                                        worst.get(label, 0.0), float(rate)
                                    )
            out = []
            for job_id in sorted(rows):
                row = rows[job_id]
                row["cache_hit_rate"] = _hit_rate(
                    row["cache_hit"], row["cache_miss"]
                )
                out.append(row)
            return out
