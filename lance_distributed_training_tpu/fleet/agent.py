"""``FleetAgent`` — a data server's membership half.

Owned by :class:`~..service.server.DataService` when
``ServeConfig.coordinator_addr`` is set: registers the server's advertise
address with the :class:`~.coordinator.Coordinator` at start, heartbeats on
a daemon thread, surfaces lease changes (generation bumps) back to the
service through ``on_lease_change``, and deregisters on stop so a graceful
shutdown reassigns the lease immediately instead of waiting out the TTL.

Failure discipline: the agent never takes the data plane down. A missing or
crashed coordinator means retry-with-backoff forever (members keep serving
the clients they have; discovery degrades, streams don't), and an
``unknown fleet member`` heartbeat answer — expiry while partitioned, or a
coordinator restart that lost the table — triggers re-registration, not an
error.
"""

from __future__ import annotations

import socket
import threading
import time
import uuid
from typing import Callable, Optional

from ..service import protocol as P
from .coordinator import UNKNOWN_MEMBER_MARKER

__all__ = ["FleetAgent"]


class FleetAgent:
    """Register + heartbeat one data server against a coordinator."""

    def __init__(
        self,
        coordinator_addr: str,
        advertise_addr: str,
        *,
        server_id: Optional[str] = None,
        num_fragments: int = 0,
        on_lease_change: Optional[Callable[[dict], None]] = None,
        counters=None,  # a ServiceCounters (optional): fleet_* keys
        heartbeat_interval_s: float = 0.0,  # 0 = coordinator-advertised
        dial_timeout_s: float = 5.0,
        backoff_s: float = 0.2,  # doubles per failure, capped at ~5s
        pressure_fn: Optional[Callable[[], dict]] = None,  # windowed
        # stall/occupancy this member reports per heartbeat (the
        # coordinator's scale-recommendation input; None = no pressure
        # field, pre-r9 heartbeat shape)
        hist_fn: Optional[Callable[[], Optional[dict]]] = None,  # v5:
        # mergeable queue-wait histogram ({counts, sum, count}) per
        # heartbeat — the coordinator sums bucket counts across members
        # into fleet-wide percentiles. None (or a None return) omits the
        # field, so pre-v5 coordinators see the exact old payload.
        jobs_fn: Optional[Callable[[], Optional[dict]]] = None,  # v6 job
        # plane: this member's per-job stats (JobPlane.stats) per
        # heartbeat, absorbed into the coordinator's JobRegistry. None
        # (or a None/empty return) omits the field — pre-v6 coordinators
        # and job-less members keep the exact old payload.
    ):
        self.coordinator_host, self.coordinator_port = P.parse_hostport(
            coordinator_addr
        )
        self.advertise_addr = advertise_addr
        self.server_id = server_id or (
            f"{advertise_addr}#{uuid.uuid4().hex[:8]}"
        )
        self.num_fragments = num_fragments
        self.on_lease_change = on_lease_change
        self.counters = counters
        self.pressure_fn = pressure_fn
        self.hist_fn = hist_fn
        self.jobs_fn = jobs_fn
        self.heartbeat_interval_s = heartbeat_interval_s
        self.dial_timeout_s = dial_timeout_s
        self.backoff_s = backoff_s
        self.lease: Optional[dict] = None
        self.generation: int = 0
        # Coordinator-advertised expiry horizon (REGISTER_OK lease_ttl_s):
        # how long this member may go silent before its lease is reaped.
        # Surfaced on /healthz so an operator can spot a heartbeat
        # interval configured dangerously close to the TTL.
        self.lease_ttl_s: float = 0.0
        self.registered = threading.Event()  # tests/healthz wait on this
        self._stop = threading.Event()
        self._paused = threading.Event()  # chaos: heartbeats held, not dead
        self._thread: Optional[threading.Thread] = None

    # -- coordinator RPC ----------------------------------------------------

    def _call(self, msg_type: int, payload: dict) -> tuple:
        """One request/reply exchange on a fresh connection — the fleet
        control plane's whole wire contract. The reply read is
        deadline-bounded (a wedged coordinator must not pin the heartbeat
        thread past a dial timeout)."""
        with socket.create_connection(
            (self.coordinator_host, self.coordinator_port),
            timeout=self.dial_timeout_s,
        ) as sock:
            P.send_msg(sock, msg_type, payload)
            return P.recv_msg(
                sock, deadline=time.monotonic() + self.dial_timeout_s
            )

    def _count(self, key: str) -> None:
        if self.counters is not None:
            self.counters.add(key)

    def _apply_lease(self, reply: dict) -> None:
        generation = int(reply.get("generation", 0))
        lease = reply.get("lease")
        changed = generation != self.generation
        self.generation = generation
        if isinstance(lease, dict):
            self.lease = lease
        if changed and self.on_lease_change is not None and self.lease:
            self.on_lease_change(dict(self.lease))

    def _register(self) -> bool:
        try:
            msg_type, reply = self._call(P.MSG_FLEET_REGISTER, {
                "server_id": self.server_id,
                "addr": self.advertise_addr,
                "num_fragments": self.num_fragments,
            })
        except (ConnectionError, OSError, P.ProtocolError):
            self._count("fleet_register_errors")
            return False
        if msg_type != P.MSG_FLEET_REGISTER_OK:
            self._count("fleet_register_errors")
            return False
        if self.heartbeat_interval_s <= 0:
            self.heartbeat_interval_s = float(
                reply.get("heartbeat_interval_s") or 2.0
            )
        self.lease_ttl_s = float(reply.get("lease_ttl_s") or 0.0)
        self._apply_lease(reply)
        self._count("fleet_registrations")
        self.registered.set()
        return True

    def _heartbeat_once(self) -> None:
        payload = {
            "server_id": self.server_id,
            "generation": self.generation,
        }
        if self.pressure_fn is not None:
            try:
                payload["pressure"] = self.pressure_fn()
            except Exception:  # noqa: BLE001 — telemetry must never kill
                pass  # the heartbeat that keeps the lease alive
        if self.hist_fn is not None:
            try:
                hist = self.hist_fn()
                if hist is not None:
                    payload["queue_wait_hist"] = hist
            except Exception:  # noqa: BLE001 — same contract as pressure
                pass
        if self.jobs_fn is not None:
            try:
                jobs = self.jobs_fn()
                if jobs:  # None/empty → field omitted (old payload shape)
                    payload["jobs"] = jobs
            except Exception:  # noqa: BLE001 — same contract as pressure
                pass
        try:
            msg_type, reply = self._call(P.MSG_FLEET_HEARTBEAT, payload)
        except (ConnectionError, OSError, P.ProtocolError):
            self._count("fleet_heartbeat_errors")
            return
        if msg_type == P.MSG_FLEET_HEARTBEAT_OK:
            self._count("fleet_heartbeats")
            self._apply_lease(reply)
        elif (
            msg_type == P.MSG_ERROR
            and UNKNOWN_MEMBER_MARKER in str(reply.get("message", ""))
        ):
            # Expired while partitioned, or the coordinator restarted and
            # lost the table — rejoin rather than beat into the void.
            self.registered.clear()
            self._register()
        else:
            self._count("fleet_heartbeat_errors")

    # -- lifecycle ----------------------------------------------------------

    def _run(self) -> None:
        backoff = self.backoff_s
        while not self._stop.is_set():
            if not self.registered.is_set():
                if self._register():
                    backoff = self.backoff_s
                else:
                    # Coordinator missing/unreachable: keep serving, keep
                    # retrying — discovery degrades, the data plane doesn't.
                    if self._stop.wait(backoff):
                        return
                    backoff = min(backoff * 2, 5.0)
                    continue
            interval = self.heartbeat_interval_s or 2.0
            if self._stop.wait(interval):
                return
            if self._paused.is_set():  # chaos partition: alive but silent
                continue
            self._heartbeat_once()

    def start(self) -> "FleetAgent":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ldt-fleet-agent"
        )
        self._thread.start()
        return self

    def stop(self, deregister: bool = True) -> None:
        """Graceful leave: halt the loop, then best-effort DEREGISTER so the
        lease reassigns now instead of at TTL expiry."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if deregister and self.registered.is_set():
            try:
                msg_type, reply = self._call(
                    P.MSG_FLEET_DEREGISTER, {"server_id": self.server_id}
                )
                if msg_type == P.MSG_FLEET_DEREGISTER_OK:
                    if self.counters is not None:
                        # The post-leave generation: what the lease table
                        # became because we left — the last fleet fact a
                        # draining member can report (a gauge, not
                        # self.generation: the heartbeat thread owns that
                        # attribute).
                        self.counters.gauge(
                            "fleet_leave_generation",
                            int(reply.get("generation") or 0),
                        )
                    self._count("fleet_deregistrations")
                else:
                    # An ERROR answer (or a future coordinator speaking a
                    # frame type this build does not know) means the lease
                    # may NOT have been released — it will go the hard way,
                    # at TTL expiry. Count it so the drain path's
                    # best-effort nature is observable (LDT1003: every
                    # inbound frame type gets a behavior, not a
                    # fall-through).
                    self._count("fleet_deregister_errors")
            except (ConnectionError, OSError, P.ProtocolError):
                pass  # coordinator gone: expiry will reap the lease
        self.registered.clear()

    def abort(self) -> None:
        """Crash-shaped leave (chaos ``kill``): no deregister — the
        coordinator finds out the hard way, at heartbeat expiry."""
        self.stop(deregister=False)

    def pause_heartbeats(self) -> None:
        """Chaos ``partition``: the server keeps serving but goes silent on
        the control plane; the coordinator expires its lease at TTL."""
        self._paused.set()

    def resume_heartbeats(self) -> None:
        self._paused.clear()
