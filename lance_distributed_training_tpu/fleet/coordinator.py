"""``Coordinator`` — the fleet's control plane.

Tracks data-server membership and owns the **shard leases**: every live
member holds a generation-numbered lease naming its stripe of the fleet and
its slice of the global fragment space. The lease table is recomputed —
generation bumped — on every membership change (register, deregister,
heartbeat expiry), and members learn their new lease in the next heartbeat
reply; clients learn the new layout from ``RESOLVE``. The coordinator never
touches batch data: the data plane stays strictly client↔server
(``FleetLoader`` stripes v3 HELLOs across the members it resolves here), so
a coordinator crash degrades discovery, not the streams in flight.

Division of authority (read this before "improving" either half): the
**stripe_index/stripe_count** in a lease and in RESOLVE is what clients
stripe by — it is the correctness-bearing part, enforced end-to-end by the
client's plan-order merge. The **fragment_lo/fragment_hi** slice is
*advisory*: servers stay stateless decode planes that can serve any step of
any plan (that statelessness is exactly what makes failover a pure client
re-stripe), so the fragment slice does not gate what a server will serve.
It exists for operators (capacity math on /healthz: which member owns how
much of the dataset at the current generation) and for locality-aware
read-ahead, and a lease *change* is the signal members key cache
invalidation on (``DataService._on_lease_change`` drops its plan cache).

Protocol: the fleet message types of :mod:`..service.protocol` — one
request, one reply, per short-lived connection. No streaming state means a
wedged peer costs one handler thread for one ``handshake_timeout_s``
deadline, nothing more.

Thread & queue policy (``ldt check`` LDT201/LDT203): every thread is
``daemon=True``; every control recv carries a deadline. The coordinator has
no queues — its whole state is the lease table under one lock.

Lock discipline (LDT1001/LDT1002 audit, r9): ``_lock`` guards the member
table and generation counter across seven sites — the four request
handlers, the expiry sweep, ``_healthz``, and the ``serve_forever`` status
line — and is NEVER held across socket I/O or logging. Every handler
builds its reply dict *inside* the critical section and sends it *after*
release (``_handle_conn`` owns the ``send_msg``); ``_expire_loop`` and the
handlers log after releasing. A heartbeat reply sent under the lease-table
lock would serialize the whole control plane behind one slow peer's TCP
window — the cross-module lock model keeps that shape a lint failure, not
a code-review hope. The registry counter/gauge calls inside
``_rebalance_locked`` do nest the registry's internal lock under ``_lock``
(a ``coordinator._lock → registry._lock`` edge in ``ldt graph``); that
order is acyclic program-wide because the registry never calls back out.
"""

from __future__ import annotations

import dataclasses
import socket
import threading
import time
from typing import Optional

from ..obs.registry import (
    DEFAULT_MS_BUCKETS,
    MetricsRegistry,
    default_registry,
    percentile_from_counts,
)
from ..obs.spans import span
from ..service import protocol as P
from .jobs import JobRegistry

__all__ = ["CoordinatorConfig", "Coordinator", "serve_coordinator",
           "UNKNOWN_MEMBER_MARKER"]

# Error-message prefix a heartbeat from an expired/unknown member gets back.
# The agent keys its re-register path on this marker (wire prose — frozen,
# same contract as VERSION_MISMATCH_MARKER).
UNKNOWN_MEMBER_MARKER = "unknown fleet member"


@dataclasses.dataclass
class CoordinatorConfig:
    """Control-plane knobs. The data servers and trainers bring their own
    config — the coordinator only owns membership and leases."""

    host: str = "0.0.0.0"
    port: int = 8470  # 0 = ephemeral (the bound port is Coordinator.port)
    heartbeat_interval_s: float = 2.0  # advertised to members at register
    lease_ttl_s: float = 6.0  # heartbeat silence after which a member is
    # expired and its lease reassigned (>= 2-3 heartbeat intervals, so one
    # dropped packet never churns the lease table)
    handshake_timeout_s: float = 10.0  # per-connection request deadline
    log_every_s: float = 0.0  # >0: periodic membership line to stdout
    metrics_port: Optional[int] = None  # /metrics + /healthz (same contract
    # as ServeConfig.metrics_port: None = off, 0 = ephemeral)
    metrics_host: str = "127.0.0.1"  # loopback default; /healthz lists
    # member addresses unauthenticated, so non-loopback is an opt-in
    scale_up_stall_pct: float = 50.0  # a member heartbeat reporting a
    # windowed stall above this flips the fleet recommendation to
    # "scale_up" (decode-starved clients — add a member)
    scale_down_stall_pct: float = 5.0  # every member below this (with >1
    # members and clients attached) makes the fleet a "drain_candidate"
    # (capacity to spare — an operator may drain one member)
    stale_pressure_ttl_s: float = 0.0  # how long an EXPIRED member's last
    # pressure window stays on the books (tagged stale) before the
    # recommendation may trust the survivors alone; 0 = auto
    # (5 × lease_ttl_s, floor 10s). A member that stalled hot and then
    # blipped out must not flip the fleet to drain_candidate the moment
    # its lease expires — scale-down on loss-of-evidence is the one
    # direction a dropped heartbeat must never push.


class _Member:
    """One registered data server and its current lease."""

    __slots__ = ("server_id", "addr", "num_fragments", "last_heartbeat",
                 "stripe_index", "fragment_lo", "fragment_hi", "pressure",
                 "acked_generation", "queue_wait_hist", "jobs")

    def __init__(self, server_id: str, addr: str, num_fragments: int):
        self.server_id = server_id
        self.addr = addr
        self.num_fragments = num_fragments
        self.last_heartbeat = time.monotonic()
        self.stripe_index = 0
        self.fragment_lo = 0
        self.fragment_hi = 0
        # Last generation this member REPORTED in a heartbeat: lagging the
        # table's generation means the member has not yet acted on its
        # newest lease (the propagation-delay signal /healthz surfaces).
        self.acked_generation = 0
        # Latest heartbeat-reported windowed pressure ({"stall_pct": …,
        # "active_clients": …}; None until a pressure-carrying heartbeat —
        # pre-r9 members never send one and simply stay None).
        self.pressure: Optional[dict] = None
        # Latest mergeable queue-wait histogram ({"counts": [...], "sum",
        # "count"}, protocol v5) — None for pre-v5 members, exactly like
        # pressure. Bucket bounds are DEFAULT_MS_BUCKETS on both sides.
        self.queue_wait_hist: Optional[dict] = None
        # Latest per-job stats this member reported (v6 job plane) —
        # None for pre-v6 members, exactly like pressure.
        self.jobs: Optional[dict] = None

    def lease(self, generation: int, stripe_count: int) -> dict:
        return {
            "generation": generation,
            "stripe_index": self.stripe_index,
            "stripe_count": stripe_count,
            "fragment_lo": self.fragment_lo,
            "fragment_hi": self.fragment_hi,
        }


class Coordinator:
    """Serve fleet membership + shard leases over TCP until :meth:`stop`."""

    def __init__(self, config: CoordinatorConfig,
                 registry: Optional[MetricsRegistry] = None):
        self.config = config
        self.registry = registry if registry is not None else default_registry()
        self._members: dict[str, _Member] = {}
        self._lock = threading.Lock()
        self.generation = 0
        # Fleet-wide job view (v6): declared via RESOLVE payloads, fed by
        # heartbeat `jobs` stats. Own (leaf) lock — safe to call under
        # `_lock` (same acyclic shape as the registry gauges).
        self.jobs = JobRegistry()
        # Expired members' last pressure windows, tagged stale (guarded
        # by `_lock`): server_id -> pressure dict + "expired_at"
        # monotonic stamp. Retained for stale_pressure_ttl_s so a hot
        # member's heartbeat blip cannot flip the recommendation to
        # drain_candidate on loss of evidence; pruned by the expiry
        # sweep, replaced by fresh evidence on re-register.
        self._stale_pressure: dict[str, dict] = {}
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._expiry_thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self.port: Optional[int] = None
        self._metrics = None
        self.metrics_port: Optional[int] = None

    # -- lease table --------------------------------------------------------

    def _rebalance_locked(self) -> None:
        """Recompute every member's lease (caller holds ``_lock``): stripes
        by sorted server_id (deterministic across coordinator restarts), the
        fragment space split into contiguous near-equal slices. Bumps the
        generation — the one number every cache keys on."""
        t0 = time.perf_counter()
        self.generation += 1
        members = sorted(self._members.values(), key=lambda m: m.server_id)
        count = len(members)
        total_fragments = max(
            (m.num_fragments for m in members), default=0
        )
        for i, m in enumerate(members):
            m.stripe_index = i
            if count and total_fragments:
                lo = (total_fragments * i) // count
                hi = (total_fragments * (i + 1)) // count
            else:
                lo = hi = 0
            m.fragment_lo, m.fragment_hi = lo, hi
        self.registry.gauge("fleet_members").set(count)
        self.registry.gauge("fleet_lease_generation").set(self.generation)
        self.registry.histogram("fleet_rebalance_ms").observe(
            (time.perf_counter() - t0) * 1e3
        )

    def _queue_wait_merged_locked(self) -> Optional[dict]:
        """Fleet-wide queue-wait percentiles from the members' v5
        heartbeat histograms (caller holds ``_lock``): sum the bucket
        count vectors — histograms with shared bounds merge exactly, the
        property per-member p99 gauges don't have — then interpolate.
        None before any well-formed report."""
        n = len(DEFAULT_MS_BUCKETS) + 1  # finite bounds + the +Inf slot
        summed = [0] * n
        members = total = 0
        for m in self._members.values():
            h = m.queue_wait_hist
            if not isinstance(h, dict):
                continue
            counts = h.get("counts")
            # Shape guard: a foreign/els future member whose bucket layout
            # differs cannot be merged — skip it rather than corrupt the
            # fleet aggregate.
            if not isinstance(counts, list) or len(counts) != n:
                continue
            try:
                counts = [int(c) for c in counts]
            except (TypeError, ValueError):
                continue
            members += 1
            total += sum(counts)
            for j, c in enumerate(counts):
                summed[j] += c
        if not members or total <= 0:
            return None
        return {
            "members": members,
            "count": total,
            "p50_ms": round(percentile_from_counts(
                DEFAULT_MS_BUCKETS, summed, total, 50), 3),
            "p95_ms": round(percentile_from_counts(
                DEFAULT_MS_BUCKETS, summed, total, 95), 3),
            "p99_ms": round(percentile_from_counts(
                DEFAULT_MS_BUCKETS, summed, total, 99), 3),
        }

    def _members_payload_locked(self) -> dict:
        now = time.monotonic()
        members = sorted(self._members.values(), key=lambda m: m.server_id)
        return {
            "generation": self.generation,
            "stripe_count": len(members),
            "members": [
                {
                    "server_id": m.server_id,
                    "addr": m.addr,
                    "stripe_index": m.stripe_index,
                    "fragment_lo": m.fragment_lo,
                    "fragment_hi": m.fragment_hi,
                    "heartbeat_age_s": round(now - m.last_heartbeat, 3),
                    "acked_generation": m.acked_generation,
                    "pressure": m.pressure,
                }
                for m in members
            ],
            "queue_wait_ms": self._queue_wait_merged_locked(),
            "recommendation": self._recommend_locked(),
            # v6 job plane: fleet-wide per-job rows (additive key — old
            # clients ignore it, like every RESOLVE extension).
            "jobs": self.jobs.payload(),
            # Expired members whose last pressure window is still on the
            # books (see _expire_loop) — the evidence the recommendation
            # refuses to scale down against.
            "stale_members": [
                {
                    "server_id": sid,
                    "pressure": {
                        k: v for k, v in entry.items() if k != "expired_at"
                    },
                    "stale_age_s": round(
                        now - entry.get("expired_at", now), 3
                    ),
                }
                for sid, entry in sorted(self._stale_pressure.items())
            ],
        }

    def _recommend_locked(self) -> dict:
        """Aggregate the members' heartbeat-reported pressure into one
        scale recommendation (caller holds ``_lock``). Advisory by design —
        the coordinator never spawns or kills members; an operator (or a
        later PR's autoscaler) acts on ``ldt fleet recommend`` /
        ``/healthz`` / the ``fleet_scale_recommendation`` gauge.

        * any member's windowed stall >= ``scale_up_stall_pct`` →
          ``scale_up`` (its clients are decode-starved; add a member),
        * every reporting member <= ``scale_down_stall_pct`` with clients
          attached and >1 members → ``drain_candidate`` (capacity to
          spare),
        * otherwise (or before any pressure report) → ``ok``.
        """
        reported = [
            m for m in self._members.values()
            if isinstance(m.pressure, dict)
        ]
        if not reported:
            return {"action": "ok", "code": 0,
                    "reason": "no pressure reports yet"}
        worst = max(reported,
                    key=lambda m: m.pressure.get("stall_pct", 0.0))
        worst_stall = float(worst.pressure.get("stall_pct", 0.0))
        cfg = self.config
        if worst_stall >= cfg.scale_up_stall_pct:
            return {
                "action": "scale_up", "code": 1,
                "member": worst.server_id,
                "stall_pct": worst_stall,
                "reason": (
                    f"member {worst.server_id} stall "
                    f"{worst_stall:.1f}% >= {cfg.scale_up_stall_pct:.1f}%"
                ),
            }
        serving = [
            m for m in reported
            if m.pressure.get("active_clients", 0)
        ]
        if (
            len(self._members) > 1
            and serving
            and worst_stall <= cfg.scale_down_stall_pct
        ):
            # Loss-of-evidence guard: an EXPIRED member whose last window
            # was hotter than the drain band blocks drain_candidate while
            # its stale pressure is retained. The survivors looking calm
            # right after a hot member blipped out is exactly when the
            # fleet must NOT shed capacity — expiry already shrank it.
            stale_hot = sorted(
                sid for sid, entry in self._stale_pressure.items()
                if float(entry.get("stall_pct", 0.0))
                > cfg.scale_down_stall_pct
            )
            if stale_hot:
                return {
                    "action": "ok", "code": 0,
                    "stall_pct": worst_stall,
                    "reason": (
                        f"drain withheld: expired member(s) {stale_hot} "
                        "last reported stall above "
                        f"{cfg.scale_down_stall_pct:.1f}% — evidence "
                        "stale, not absent"
                    ),
                }
            return {
                "action": "drain_candidate", "code": -1,
                "stall_pct": worst_stall,
                "reason": (
                    f"all members <= {cfg.scale_down_stall_pct:.1f}% "
                    "stall with clients attached — capacity to spare"
                ),
            }
        return {"action": "ok", "code": 0, "stall_pct": worst_stall,
                "reason": "pressure within band"}

    # -- request handlers ---------------------------------------------------

    def _handle_register(self, req: dict) -> tuple:
        server_id = str(req.get("server_id") or "")
        addr = str(req.get("addr") or "")
        if not server_id or not addr:
            return P.MSG_ERROR, {"message": "register needs server_id + addr"}
        P.parse_hostport(addr)  # reject an undialable advertise addr loudly
        num_fragments = int(req.get("num_fragments") or 0)
        with self._lock:
            known = self._members.get(server_id)
            if known is not None and known.addr == addr:
                # Idempotent re-register (agent retry, partition heal with
                # nothing else changed): refresh liveness, same lease table.
                known.last_heartbeat = time.monotonic()
                known.num_fragments = num_fragments or known.num_fragments
            else:
                self._members[server_id] = _Member(
                    server_id, addr, num_fragments
                )
                # Fresh member, fresh evidence: its live heartbeats
                # supersede any stale window it left behind on expiry.
                self._stale_pressure.pop(server_id, None)
                self._rebalance_locked()
            member = self._members[server_id]
            reply = {
                "generation": self.generation,
                "heartbeat_interval_s": self.config.heartbeat_interval_s,
                "lease_ttl_s": self.config.lease_ttl_s,
                "lease": member.lease(self.generation, len(self._members)),
            }
        self.registry.counter("fleet_registrations_total").inc()
        self._log(f"member {server_id} registered at {addr} "
                  f"(generation {reply['generation']})")
        return P.MSG_FLEET_REGISTER_OK, reply

    def _handle_heartbeat(self, req: dict) -> tuple:
        server_id = str(req.get("server_id") or "")
        # Field-TYPE validation BEFORE any state moves (the same
        # discipline protocol.hello_malformed gives the HELLO): a
        # malformed heartbeat must neither refresh the member's liveness
        # nor die as a ValueError repr — answer a diagnosable rejection
        # and leave the lease clock untouched.
        gen = req.get("generation")
        if gen is not None and not P.is_json_int(gen):
            return P.MSG_ERROR, {"message": (
                "malformed heartbeat field 'generation': expected "
                f"integer, got {type(gen).__name__} {gen!r}"
            )}
        with self._lock:
            member = self._members.get(server_id)
            if member is None:
                # Expired (or a coordinator restart lost the table): the
                # agent re-registers on this marker instead of beating into
                # the void forever.
                return P.MSG_ERROR, {
                    "message": f"{UNKNOWN_MEMBER_MARKER}: {server_id!r} — "
                               "re-register"
                }
            member.last_heartbeat = time.monotonic()
            if gen is not None:
                # The generation the member is acting on: a lag against
                # self.generation means its lease reply is still in
                # flight (or it is re-planning) — visible per member on
                # /healthz. A heartbeat WITHOUT the field (a minimal
                # foreign peer) keeps the last known value rather than
                # fabricating a permanent generation-0 stuck-lease
                # signal.
                member.acked_generation = int(gen)
            pressure = req.get("pressure")
            if isinstance(pressure, dict):
                member.pressure = dict(pressure)
            hist = req.get("queue_wait_hist")
            if isinstance(hist, dict):
                # Stored as-reported; shape-validated at merge time so one
                # malformed member degrades to "not reporting", never to a
                # poisoned aggregate.
                member.queue_wait_hist = dict(hist)
            jobs = req.get("jobs")
            if isinstance(jobs, dict):
                # v6 job plane: stored as-reported (shape-guarded by the
                # JobRegistry on absorption, same degrade-to-not-reporting
                # posture as the histogram above).
                member.jobs = dict(jobs)
            recommendation = self._recommend_locked()
            stalls = [
                float(m.pressure.get("stall_pct", 0.0))
                for m in self._members.values()
                if isinstance(m.pressure, dict)
            ]
            queue_wait = self._queue_wait_merged_locked()
            reply = {
                "generation": self.generation,
                "lease": member.lease(self.generation, len(self._members)),
            }
        self.registry.counter("fleet_heartbeats_total").inc()
        # Pressure surface (autotune fleet half): scraped series an
        # operator's alerting keys on, refreshed per heartbeat. Set outside
        # the lock — the registry has its own.
        if stalls:
            self.registry.gauge("fleet_pressure_stall_pct_max").set(
                max(stalls)
            )
            self.registry.gauge("fleet_pressure_stall_pct_mean").set(
                sum(stalls) / len(stalls)
            )
        if queue_wait is not None:
            # Fleet SLO surface (v5): exact cross-member percentiles from
            # summed bucket counts — same outside-the-lock discipline as
            # the pressure gauges above.
            for q in (50, 95, 99):
                self.registry.gauge(f"fleet_queue_wait_p{q}_ms").set(
                    queue_wait[f"p{q}_ms"]
                )
        self.registry.gauge("fleet_scale_recommendation").set(
            recommendation.get("code", 0)
        )
        if isinstance(jobs, dict):
            # Outside `_lock` (the JobRegistry lock is a leaf of its own).
            self.jobs.observe_member(server_id, jobs)
        return P.MSG_FLEET_HEARTBEAT_OK, reply

    def _handle_deregister(self, req: dict) -> tuple:
        server_id = str(req.get("server_id") or "")
        with self._lock:
            if self._members.pop(server_id, None) is not None:
                # A graceful leave is EVIDENCE, not a blip: no stale
                # pressure retained (contrast _expire_loop).
                self._stale_pressure.pop(server_id, None)
                self._rebalance_locked()
            generation = self.generation
        self.jobs.drop_member(server_id)
        self.registry.counter("fleet_deregistrations_total").inc()
        self._log(f"member {server_id} deregistered "
                  f"(generation {generation})")
        return P.MSG_FLEET_DEREGISTER_OK, {"generation": generation}

    def _handle_resolve(self, req: dict) -> tuple:
        # v6 job plane: a resolving client may declare its job so the
        # registry lists the tenant before any member has served it.
        # Unknown/absent fields are simply ignored (a pre-v6 client's
        # empty payload is the common case) — declare() validates types.
        self.jobs.declare(req.get("job_id"), req.get("job_priority"))
        with self._lock:
            payload = self._members_payload_locked()
        self.registry.counter("fleet_resolves_total").inc()
        return P.MSG_FLEET_RESOLVE_OK, payload

    # -- expiry -------------------------------------------------------------

    def _stale_pressure_ttl(self) -> float:
        """Retention horizon for an expired member's last pressure window
        (``stale_pressure_ttl_s``; 0 = 5 heartbeat-expiry TTLs, floor
        10s — long enough for an operator or autoscaler poll cycle to
        see the withheld-drain reason, short enough that a genuinely
        departed member stops haunting the recommendation)."""
        cfg = self.config
        if cfg.stale_pressure_ttl_s > 0:
            return float(cfg.stale_pressure_ttl_s)
        return max(5.0 * cfg.lease_ttl_s, 10.0)

    def _expire_loop(self) -> None:
        ttl = self.config.lease_ttl_s
        poll = max(min(ttl / 4.0, 1.0), 0.05)
        while not self._stopped.wait(poll):
            now = time.monotonic()
            expired = []
            with self._lock:
                for server_id, m in list(self._members.items()):
                    if now - m.last_heartbeat > ttl:
                        expired.append(server_id)
                        # Retain the last pressure window, tagged stale,
                        # before the member record dies: expiry used to
                        # drop it silently, and the survivors' calm would
                        # flip the recommendation to drain_candidate on
                        # the very blip that just shrank the fleet (the
                        # _recommend_locked loss-of-evidence guard).
                        if isinstance(m.pressure, dict):
                            self._stale_pressure[server_id] = dict(
                                m.pressure, stale=True, expired_at=now
                            )
                        del self._members[server_id]
                retention = self._stale_pressure_ttl()
                for server_id in [
                    sid for sid, entry in self._stale_pressure.items()
                    if now - entry.get("expired_at", now) > retention
                ]:
                    del self._stale_pressure[server_id]
                if expired:
                    self._rebalance_locked()
                    generation = self.generation
            for server_id in expired:
                self.jobs.drop_member(server_id)
            if expired:
                self.registry.counter("fleet_expirations_total").inc(
                    len(expired)
                )
                self._log(
                    f"expired {expired} after {ttl}s heartbeat silence "
                    f"(generation {generation})"
                )

    # -- control plane ------------------------------------------------------

    def start(self) -> "Coordinator":
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((self.config.host, self.config.port))
            sock.listen(64)
        except BaseException:
            # A failed bind (port in use) must not leak the listener fd
            # (LDT1201: the caller retries start(), each leak is forever).
            sock.close()
            raise
        self._sock = sock
        self.port = sock.getsockname()[1]
        if self.config.metrics_port is not None:
            from ..obs.http import MetricsHTTPServer

            try:
                self._metrics = MetricsHTTPServer(
                    self.registry,
                    port=self.config.metrics_port,
                    host=self.config.metrics_host,
                    healthz_fn=self._healthz,
                ).start()
            except BaseException:
                # Any exporter-start failure (not just a bind OSError)
                # must retract the listener: the caller has no handle to
                # a half-initialized service, so the fd would leak.
                sock.close()
                self._sock = None
                raise
            self.metrics_port = self._metrics.port
            self._log(f"metrics on :{self.metrics_port} (/metrics, /healthz)")
        # Gauges exist from second zero — a scrape of an empty fleet reads
        # 0 members / generation 0, not absent series.
        self.registry.gauge("fleet_members").set(0)
        self.registry.gauge("fleet_lease_generation").set(self.generation)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="ldt-fleet-accept"
        )
        self._accept_thread.start()
        self._expiry_thread = threading.Thread(
            target=self._expire_loop, daemon=True, name="ldt-fleet-expiry"
        )
        self._expiry_thread.start()
        self._log(f"coordinating on {self.config.host}:{self.port}")
        return self

    def _healthz(self) -> dict:
        with self._lock:
            payload = self._members_payload_locked()
        stopped = self._stopped.is_set()
        payload["status"] = "degraded" if stopped else "ok"
        payload["lease_ttl_s"] = self.config.lease_ttl_s
        payload["heartbeat_interval_s"] = self.config.heartbeat_interval_s
        from ..obs.http import build_info

        payload["build"] = build_info()
        return payload

    def _accept_loop(self) -> None:
        assert self._sock is not None
        while not self._stopped.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:  # listener closed by stop()
                return
            threading.Thread(
                target=self._handle_conn, args=(conn, f"{addr[0]}:{addr[1]}"),
                daemon=True, name=f"ldt-fleet-conn-{addr[1]}",
            ).start()

    def _handle_conn(self, conn: socket.socket, peer: str) -> None:
        """One request, one reply, close — the control-plane handshake. The
        deadline bounds the whole request read (a silent peer is dropped,
        LDT203), and any reply-side error just abandons the connection."""
        try:
            timeout = self.config.handshake_timeout_s
            deadline = time.monotonic() + timeout if timeout > 0 else None
            msg_type, req = P.recv_msg(conn, deadline=deadline)
            handler = {
                P.MSG_FLEET_REGISTER: self._handle_register,
                P.MSG_FLEET_HEARTBEAT: self._handle_heartbeat,
                P.MSG_FLEET_DEREGISTER: self._handle_deregister,
                P.MSG_FLEET_RESOLVE: self._handle_resolve,
            }.get(msg_type)
            if handler is None:
                reply_type, reply = P.MSG_ERROR, {
                    "message": f"unexpected fleet message type {msg_type}"
                }
            else:
                try:
                    # Spanned so a coordinator run with LDT_TRACE_PATH set
                    # appears on the merged fleet timeline (the control
                    # plane's track next to the data-plane flows).
                    with span("coord.handle", msg_type=msg_type, peer=peer):
                        reply_type, reply = handler(req)
                except (ValueError, TypeError, KeyError) as exc:
                    reply_type, reply = P.MSG_ERROR, {"message": repr(exc)}
            P.send_msg(conn, reply_type, reply)
        except (ConnectionError, OSError, P.ProtocolError):
            pass  # dead/garbage peer: nothing to answer
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def serve_forever(self) -> None:
        """Blocking serve (the ``ldt coordinator`` entry). SIGTERM (docker
        stop, k8s preemption) and KeyboardInterrupt both drain through
        :meth:`stop` — the lease table dies with the process, members
        re-register against a successor."""
        from ..utils.signals import install_sigterm_handler

        if self._sock is None:
            self.start()
        install_sigterm_handler(self._stopped.set)
        try:
            interval = self.config.log_every_s
            while not self._stopped.wait(interval if interval > 0 else 3600.0):
                if interval > 0:
                    with self._lock:
                        line = self._members_payload_locked()
                    self._log(f"generation {line['generation']}, "
                              f"{line['stripe_count']} members")
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def stop(self) -> None:
        self._stopped.set()
        if self._metrics is not None:
            self._metrics.stop()
            self._metrics = None
        if self._sock is not None:
            try:
                # shutdown wakes a concurrently-blocked accept(); a bare
                # close can leave the kernel listener alive while the
                # syscall holds the last reference (see DataService.stop).
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        if self._expiry_thread is not None:
            self._expiry_thread.join(timeout=2.0)

    def __enter__(self) -> "Coordinator":
        return self.start() if self._sock is None else self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _log(self, msg: str) -> None:
        print(f"[coordinator] {msg}", flush=True)


def serve_coordinator(config: CoordinatorConfig) -> None:
    """Module-level convenience for the CLI."""
    Coordinator(config).serve_forever()
