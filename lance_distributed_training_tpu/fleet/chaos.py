"""Deterministic fault injection for fleet members — failover is *tested*.

A failover path that only runs in production outages is an untested path.
This module gives tests (and the CI fleet smoke) scripted control over a
member server's failure modes, deterministically:

* **kill** — the crash shape: every socket (listener + live sessions) is
  closed mid-stream with no ``MSG_END``, heartbeats stop with no
  ``DEREGISTER``. Clients see a dropped connection; the coordinator finds
  out at heartbeat expiry. ``kill_after(n)`` arms the kill to fire
  synchronously in the server's sender thread after *exactly* ``n`` batch
  frames have been sent — the test knows precisely which step the failover
  resumes from, every run.
* **stall** — the slow-server shape: the sender thread blocks before the
  n-th send for a scripted duration. No connection drops, so a correct
  client waits (a stall must NOT trigger failover — that's the livelock
  the no-mid-stream-deadline policy exists to prevent).
* **partition** — the control-plane-only cut: heartbeats pause (the
  coordinator expires the lease at TTL) while the data plane keeps
  serving. ``heal()`` resumes heartbeats and the agent re-registers on the
  ``unknown fleet member`` answer.

The injection point is ``DataService.chaos`` — a callable the sender loop
invokes before each batch send (``chaos("send", peer, step)``). In-thread
execution is what makes the schedule deterministic: the k-th send is the
k-th hook call, regardless of thread scheduling or wall clocks.
"""

from __future__ import annotations

import threading
from typing import Optional

__all__ = ["ChaosController"]


class ChaosController:
    """Scripted kill/stall/partition of ONE member server under test
    control. Construct with the member's :class:`DataService` (and its
    fleet agent, when registered); the controller installs itself as the
    service's chaos hook."""

    def __init__(self, service, agent=None):
        self.service = service
        self.agent = agent if agent is not None else getattr(
            service, "fleet_agent", None
        )
        self._lock = threading.Lock()
        self._sends = 0
        self._kill_at: Optional[int] = None
        self._stall_at: Optional[int] = None
        self._stall_s = 0.0
        self._stalled = threading.Event()  # test sync: stall reached
        self.killed = threading.Event()  # test sync: kill fired
        service.chaos = self._hook

    # -- scripting ----------------------------------------------------------

    def kill_after(self, batches: int) -> "ChaosController":
        """Arm an abrupt kill to fire after exactly ``batches`` batch
        frames have crossed the wire (fleet-wide, all sessions)."""
        with self._lock:
            self._kill_at = int(batches)
        return self

    def stall_after(self, batches: int, seconds: float) -> "ChaosController":
        """Arm a sender stall of ``seconds`` before send ``batches + 1``."""
        with self._lock:
            self._stall_at = int(batches)
            self._stall_s = float(seconds)
        return self

    @property
    def batches_sent(self) -> int:
        with self._lock:
            return self._sends

    # -- immediate actions --------------------------------------------------

    def kill_now(self) -> None:
        """SIGKILL shape, in-process: no END frames, no deregister, every
        socket closed. Idempotent."""
        if self.killed.is_set():
            return
        self.killed.set()
        if self.agent is not None:
            self.agent.abort()
        # DataService.stop() closes the listener and every session socket
        # without sending MSG_END — from a peer's point of view that IS the
        # crash: connection reset mid-stream.
        self.service.stop()

    def partition(self) -> None:
        """Cut the control plane only: heartbeats pause, data keeps
        flowing; the coordinator expires the lease at TTL."""
        if self.agent is not None:
            self.agent.pause_heartbeats()

    def heal(self) -> None:
        """End a partition: heartbeats resume; the agent re-registers when
        the coordinator answers ``unknown fleet member``."""
        if self.agent is not None:
            self.agent.resume_heartbeats()

    def wait_stalled(self, timeout: float = 10.0) -> bool:
        """Block a test until an armed stall has actually been reached."""
        return self._stalled.wait(timeout)

    # -- the injection point ------------------------------------------------

    def _hook(self, event: str, peer: str, step: int) -> None:
        """Called by the server's sender thread before each batch send.
        Runs armed actions synchronously — determinism comes from being IN
        the send path, not racing it."""
        if event != "send":
            return
        with self._lock:
            self._sends += 1
            sends = self._sends
            kill = self._kill_at is not None and sends > self._kill_at
            stall = self._stall_at is not None and sends > self._stall_at
            if stall:
                self._stall_at = None  # one-shot
                stall_s = self._stall_s
        if stall:
            self._stalled.set()
            # Interruptible sleep: a concurrent kill/stop ends the stall.
            self.service._stopped.wait(stall_s)
        if kill:
            self.kill_now()
            # Abort this very send: the step armed as the kill point must
            # never reach the wire (kill_after(n) == exactly n delivered).
            raise ConnectionError("chaos: member killed")
