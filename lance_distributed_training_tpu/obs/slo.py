"""SLO plane — declared objectives with multi-window burn-rate gauges.

An SLO here is a *declared* bound on a telemetry value this process can
probe ("stall_pct stays under 10", "queue_wait_p99_ms stays under 500")
plus an error budget: the share of time the bound is allowed to be
violated. The :class:`SLOTracker` samples each objective on a daemon
ticker and publishes, per objective:

* ``slo_<name>`` — the last probed value (a gauge an alert can read
  without re-deriving the probe);
* ``slo_<name>_burn_1m`` / ``_5m`` / ``_1h`` — multi-window burn rates:
  (observed violation share over the window) / (error budget share).
  1.0 = burning budget exactly as fast as allowed; 10× on the short
  window with ~1× on the long one is the classic page-now signature,
  while a slow leak shows the reverse. Multi-window burn is what makes
  the gauges actionable instead of flappy (the Google SRE workbook's
  alerting shape, scaled down to a process-local ticker).

Objectives default to :data:`DEFAULT_SLOS` and are overridable with the
``LDT_SLOS`` env var (``"stall_pct<=10@5,queue_wait_p99_ms<=500@5"`` —
``value<=threshold@budget_pct``); probes are plain callables the owning
process wires (the DataService probes its own pressure counters, the
trainer probes the lineage histograms), returning NaN when the value is
not yet defined — NaN samples are skipped, never counted as violations.

The fleet half lives on the Coordinator: heartbeats carry mergeable
queue-wait bucket counts (version-gated like pressure), aggregated into
``fleet_queue_wait_p50/p95/p99_ms`` — see ``fleet/coordinator.py``.

Clock policy: sampling instants are ``time.monotonic()`` (windowing is a
duration computation — LDT601).
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Sequence, Tuple

from .registry import MetricsRegistry, default_registry

__all__ = [
    "SLO",
    "DEFAULT_SLOS",
    "parse_slos",
    "scoped_slos",
    "SLOTracker",
]


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declared objective: ``probe() <= threshold`` for all but
    ``budget_pct`` percent of any window."""

    name: str  # metric-safe ([a-z][a-z0-9_]*) — becomes slo_<name>*
    threshold: float
    budget_pct: float = 5.0  # allowed violation share of a window (%)


# The three objectives every data-plane deployment cares about first:
# decode starvation, end-to-end batch staleness, and queue dwell.
DEFAULT_SLOS: Tuple[SLO, ...] = (
    SLO("stall_pct", 10.0),
    SLO("batch_age_p99_ms", 2000.0),
    SLO("queue_wait_p99_ms", 500.0),
)

# Burn windows: label → seconds. Labels land in metric names, so they
# stay [a-z0-9_].
BURN_WINDOWS: Tuple[Tuple[str, float], ...] = (
    ("1m", 60.0),
    ("5m", 300.0),
    ("1h", 3600.0),
)


def parse_slos(spec: Optional[str]) -> Tuple[SLO, ...]:
    """``"name<=threshold[@budget_pct],…"`` → SLO tuple; ``None``/empty →
    :data:`DEFAULT_SLOS`. Malformed entries raise (a declared objective
    that silently vanished would be worse than a loud config error)."""
    if not spec or not spec.strip():
        return DEFAULT_SLOS
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "<=" not in part:
            raise ValueError(f"SLO {part!r}: expected name<=threshold")
        name, _, rest = part.partition("<=")
        budget = 5.0
        if "@" in rest:
            rest, _, budget_s = rest.partition("@")
            budget = float(budget_s)
        if not (0.0 < budget <= 100.0):
            raise ValueError(f"SLO {part!r}: budget_pct must be in (0, 100]")
        out.append(SLO(name.strip(), float(rest), budget))
    return tuple(out) if out else DEFAULT_SLOS


def scoped_slos(
    scope: str, slos: Optional[Sequence[SLO]] = None
) -> Tuple[SLO, ...]:
    """The given objectives re-named under a scope prefix —
    ``scoped_slos("job_tenant_a")`` turns ``stall_pct`` into
    ``job_tenant_a_stall_pct`` with the threshold and budget unchanged.

    This is how per-tenant burn-down rides the label-less registry
    (``obs/registry.py`` deliberately has no label dimension — LDT601
    name discipline instead): a scope IS a name prefix, so one
    :class:`SLOTracker` per job publishes ``slo_job_<slug>_stall_pct``
    and its burn windows next to the fleet-wide series. ``scope`` must
    itself be metric-safe (``[a-z][a-z0-9_]*`` — callers sanitize via
    ``fleet.jobs.job_slug``). ``slos=None`` scopes the ``LDT_SLOS``
    env-var objectives, like :class:`SLOTracker` itself."""
    if slos is None:
        slos = parse_slos(os.environ.get("LDT_SLOS"))
    return tuple(
        SLO(f"{scope}_{s.name}", s.threshold, s.budget_pct) for s in slos
    )


class SLOTracker:
    """Sample declared SLO probes and publish burn-rate gauges.

    ``probes`` maps objective name → zero-arg callable returning the
    current value (NaN = undefined, sample skipped). Objectives without
    a probe are ignored for this tracker — the trainer and the server
    declare the same SLO set but can each probe only their own half.
    A probe that raises is treated as NaN: telemetry must never kill
    the ticker (the heartbeat posture, ``fleet/agent.py``).
    """

    def __init__(
        self,
        probes: Dict[str, Callable[[], float]],
        slos: Optional[Sequence[SLO]] = None,
        registry: Optional[MetricsRegistry] = None,
        interval_s: float = 5.0,
    ):
        if slos is None:
            slos = parse_slos(os.environ.get("LDT_SLOS"))
        self.slos = tuple(s for s in slos if s.name in probes)
        self.probes = dict(probes)
        self.registry = (
            registry if registry is not None else default_registry()
        )
        self.interval_s = max(0.1, float(interval_s))
        # Per-objective (monotonic instant, violated) samples; bounded by
        # count (the longest window / interval, plus slack) AND trimmed by
        # age at read — memory stays fixed forever.
        horizon = max(seconds for _, seconds in BURN_WINDOWS)
        cap = int(horizon / self.interval_s) + 8
        self._samples: Dict[str, deque] = {
            s.name: deque(maxlen=cap) for s in self.slos
        }
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- sampling ----------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> None:
        """One sampling pass (the ticker calls this; tests call it
        directly with a synthetic ``now``)."""
        now = time.monotonic() if now is None else now
        for slo in self.slos:
            try:
                value = float(self.probes[slo.name]())
            except Exception:  # noqa: BLE001 — telemetry must never
                value = math.nan  # kill the ticker
            if math.isnan(value):
                continue
            self.registry.gauge(f"slo_{slo.name}").set(round(value, 3))
            samples = self._samples[slo.name]
            samples.append((now, value > slo.threshold))
            for label, seconds in BURN_WINDOWS:
                lo = now - seconds
                total = bad = 0
                for t, violated in samples:
                    if t >= lo:
                        total += 1
                        bad += violated
                if total:
                    burn = (100.0 * bad / total) / slo.budget_pct
                    self.registry.gauge(
                        f"slo_{slo.name}_burn_{label}"
                    ).set(round(burn, 3))

    def status(self) -> Dict[str, dict]:
        """``{name: {value, threshold, budget_pct, burn: {label: x}}}`` —
        the ``/healthz``-friendly view of the published gauges."""
        out: Dict[str, dict] = {}
        for slo in self.slos:
            value_g = self.registry.get(f"slo_{slo.name}")
            if value_g is None:
                continue
            burn = {}
            for label, _ in BURN_WINDOWS:
                g = self.registry.get(f"slo_{slo.name}_burn_{label}")
                if g is not None:
                    burn[label] = g.value
            out[slo.name] = {
                "value": value_g.value,
                "threshold": slo.threshold,
                "budget_pct": slo.budget_pct,
                "burn": burn,
            }
        return out

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SLOTracker":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="ldt-slo-tick"
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
            self._thread = None
