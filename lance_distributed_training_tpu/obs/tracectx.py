"""Cross-process trace context — the causal thread a batch carries.

A trace context is a tiny JSON-safe dict, W3C-trace-context shaped::

    {"trace_id": "32 hex chars",        # one per batch, born at decode
     "span_id": "16 hex chars",         # the stamping process's segment
     "parent_span_id": "16 hex chars"}  # absent on the root segment

It is stamped ONCE per plan item at decode (``DataService._produce`` /
the in-process pipeline's decode seam) and then *propagated*: the sender
ships it in the versioned batch meta next to lineage (protocol v5,
optional field — old peers interop exactly like the v1/v2 lineage
negotiation), and every receiving hop derives a :func:`child` context
whose ``parent_span_id`` is the remote segment's ``span_id``. Each hop
also attaches the context to its local :mod:`.spans` span as
``trace_id`` / ``trace_span`` / ``trace_parent`` attrs, which is what
lets ``ldt trace export`` stitch per-process JSONLs into ONE Perfetto
trace with real parent edges across decode → queue → wire → merge →
placement → step, and what ``ldt trace critical-path`` joins on.

Ids come from ``os.urandom`` — pure entropy, never a seeded RNG (the
deterministic-stream RNGs are content-bearing; trace ids must never be,
and LDT1301 would flag a seeded generator reaching the wire meta). A
trace context is telemetry: it rides the meta, it never influences plan,
batch bytes, or cursor state.

Like lineage, a context that arrives off the wire is arbitrary peer
JSON: :func:`coerce_trace` validates shape and bounds and returns
``None`` for anything malformed — a corrupt optional-telemetry field
must never kill a receive loop.
"""

from __future__ import annotations

import binascii
import os
from typing import Dict, Optional

__all__ = [
    "make_trace",
    "child",
    "coerce_trace",
    "new_trace_id",
    "new_span_id",
]

# Hex-string lengths (W3C trace-context sizes: 16-byte trace id,
# 8-byte span id).
_TRACE_ID_LEN = 32
_SPAN_ID_LEN = 16


def new_trace_id() -> str:
    """32 hex chars of pure entropy — one per batch lifetime."""
    return binascii.hexlify(os.urandom(_TRACE_ID_LEN // 2)).decode("ascii")


def new_span_id() -> str:
    """16 hex chars of pure entropy — one per process-local segment."""
    return binascii.hexlify(os.urandom(_SPAN_ID_LEN // 2)).decode("ascii")


def make_trace() -> Dict[str, str]:
    """Root context, stamped at plan-item decode (the batch's birth)."""
    return {"trace_id": new_trace_id(), "span_id": new_span_id()}


def child(trace: Dict[str, str]) -> Dict[str, str]:
    """The next hop's context: same trace, fresh segment id, parent
    edge back to the hop that handed us the batch."""
    return {
        "trace_id": trace["trace_id"],
        "span_id": new_span_id(),
        "parent_span_id": trace["span_id"],
    }


def _hex_id(value, max_len: int) -> Optional[str]:
    """A peer-supplied id: a lowercase-hex string of sane length, or
    None. Bounds first — a multi-MB "id" must not survive into span
    attrs and trace files."""
    if not isinstance(value, str) or not 1 <= len(value) <= max_len:
        return None
    try:
        int(value, 16)
    except ValueError:
        return None
    return value.lower()


def coerce_trace(obj) -> Optional[Dict[str, str]]:
    """Validate a wire-supplied trace context (arbitrary peer JSON) into
    a well-formed one, or ``None``. Mirrors lineage's ``_as_number``
    posture: malformed optional telemetry is dropped, never raised on —
    and absence is interop (an old-protocol peer), not an error."""
    if not isinstance(obj, dict):
        return None
    trace_id = _hex_id(obj.get("trace_id"), _TRACE_ID_LEN)
    span_id = _hex_id(obj.get("span_id"), _SPAN_ID_LEN)
    if trace_id is None or span_id is None:
        return None
    out = {"trace_id": trace_id, "span_id": span_id}
    parent = _hex_id(obj.get("parent_span_id"), _SPAN_ID_LEN)
    if parent is not None:
        out["parent_span_id"] = parent
    return out
