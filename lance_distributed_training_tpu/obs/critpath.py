"""Critical-path analysis over merged cross-process traces.

Input is the merged event list ``ldt trace export`` assembles from
per-process span JSONLs. Three layers of machinery live here:

* **clock rebasing** — span timestamps are per-process monotonic
  microseconds; each process's JSONL carries one ``ldt.clock_sync``
  anchor (wall_ns + mono_ns captured together, the LDT601-sanctioned
  epoch stamp) so all processes can be placed on one wall timeline.
  Loopback-accurate; across real hosts it inherits NTP skew exactly as
  lineage ``wire_ms`` does, and negative gaps clamp to zero.
* **flow stitching** — events sharing an ``args.trace_id`` (stamped by
  :mod:`.tracectx` at decode, propagated over protocol v5) become one
  Perfetto flow: arrows decode → send → merge across process tracks,
  with the true parent edge (``trace_parent`` = the remote segment's
  ``trace_span``) preserved in args.
* **attribution** — per batch (one trace id), tile the wall from decode
  start to step end into named segments::

      decode | cache   svc.decode duration (cache when the probe hit)
      queue_wait       svc.decode end → svc.send start (same clock)
      wire             svc.send start → receive start (rebased, clamped
                       — includes the send span itself: serialize +
                       socket write ride this segment, so the tiling
                       has no hole the size of every send)
      merge            receive-hop duration (client-side decode)
      h2d              receive end → train.step start (transform +
                       placement + prefetch dwell — the client's lap)
      step             train.step duration

  ``coverage_pct`` = attributed / wall. The tiling is exhaustive by
  construction, so coverage only drops when clock skew eats a gap —
  which is why the smoke asserts ≥90%, not ==100%. The straggler table
  joins the slowest chains with their cost-ledger records via the
  ``item`` attr (the BatchCache content hash) on the decode span.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

__all__ = [
    "CLOCK_SYNC_NAME",
    "DROP_MARK_NAME",
    "clock_offsets_us",
    "rebase_events",
    "flow_events",
    "dropped_spans",
    "analyze",
    "critical_path_main",
]

# Reserved JSONL record names written by obs/spans.py (ph "M"/"C"
# bookkeeping records, never rendered as duration tracks).
CLOCK_SYNC_NAME = "ldt.clock_sync"
DROP_MARK_NAME = "ldt.spans_dropped"

# Receive-hop span names (the process that pulls a batch off the wire).
_RECV_NAMES = ("client.decode", "fleet.recv")


def _args(event: dict) -> dict:
    args = event.get("args")
    return args if isinstance(args, dict) else {}


def clock_offsets_us(events: List[dict]) -> Dict[int, float]:
    """Per-pid wall-rebase offsets (µs to ADD to a monotonic ts) from
    ``ldt.clock_sync`` anchors. Multiple anchors per pid (a process that
    reopened its JSONL) keep the latest."""
    offsets: Dict[int, float] = {}
    for ev in events:
        if ev.get("name") != CLOCK_SYNC_NAME:
            continue
        args = _args(ev)
        wall, mono = args.get("wall_ns"), args.get("mono_ns")
        if isinstance(wall, (int, float)) and isinstance(mono, (int, float)):
            offsets[ev.get("pid")] = (float(wall) - float(mono)) / 1e3
    return offsets


def rebase_events(events: List[dict]) -> Tuple[List[dict], Dict[int, float]]:
    """Copy of ``events`` with every anchored pid's timestamps moved onto
    the wall timeline (µs since epoch). Unanchored pids pass through
    untouched — a single-process trace needs no alignment, and a legacy
    (pre-anchor) file stays renderable."""
    offsets = clock_offsets_us(events)
    out = []
    for ev in events:
        off = offsets.get(ev.get("pid"))
        if off is not None and isinstance(ev.get("ts"), (int, float)):
            ev = dict(ev, ts=ev["ts"] + off)
        out.append(ev)
    return out, offsets


def flow_events(events: List[dict]) -> List[dict]:
    """Perfetto flow events (ph s/t) binding each trace id's hops in
    (rebased) time order — the visible arrows decode → send → merge."""
    by_trace: Dict[str, List[dict]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        trace_id = _args(ev).get("trace_id")
        if isinstance(trace_id, str):
            by_trace.setdefault(trace_id, []).append(ev)
    flows: List[dict] = []
    for trace_id, evs in by_trace.items():
        if len(evs) < 2:
            continue
        evs.sort(key=lambda e: e.get("ts", 0.0))
        for i, ev in enumerate(evs):
            flows.append({
                "name": "batch",
                "cat": "trace",
                "ph": "s" if i == 0 else "t",
                "id": trace_id[:16],
                "pid": ev.get("pid"),
                "tid": ev.get("tid"),
                "ts": ev.get("ts", 0.0) + (ev.get("dur", 0.0) if i == 0
                                           else 0.0),
            })
    return flows


def dropped_spans(events: List[dict]) -> int:
    """Total ring-buffer drops reported by the source processes (the max
    marker value per pid — markers are cumulative counts)."""
    per_pid: Dict[int, float] = {}
    for ev in events:
        if ev.get("name") != DROP_MARK_NAME:
            continue
        dropped = _args(ev).get("dropped")
        if isinstance(dropped, (int, float)):
            pid = ev.get("pid")
            per_pid[pid] = max(per_pid.get(pid, 0.0), float(dropped))
    return int(sum(per_pid.values()))


# -- attribution -------------------------------------------------------------


def _chains(events: List[dict]) -> Dict[str, dict]:
    """Classify each trace id's hops: root decode, send, receive."""
    chains: Dict[str, dict] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = _args(ev)
        trace_id = args.get("trace_id")
        if not isinstance(trace_id, str):
            continue
        chain = chains.setdefault(trace_id, {"pids": set()})
        chain["pids"].add(ev.get("pid"))
        name = ev.get("name", "")
        if args.get("trace_parent") is not None or name in _RECV_NAMES:
            chain["recv"] = ev
        elif name.endswith(".send"):
            chain["send"] = ev
        elif args.get("trace_span") is not None:
            chain["root"] = ev
        if "step" in args and "step" not in chain:
            chain["step_no"] = args["step"]
    return chains


def _join_trainer(chains: Dict[str, dict], events: List[dict]) -> None:
    """Attach each chain's train.step span by step number: the trainer's
    spans predate trace propagation into the step function, so the join
    key is the step attr — picking the first step span at/after the
    chain's receive hop (multi-epoch runs reuse plan step numbers).

    Only chains WITH a receive hop join: a sent-but-never-merged chain
    (a stripe reconnect re-decodes its steps under fresh trace ids and
    abandons the in-flight frames) shares a step number with the chain
    that actually fed the trainer — joining it by number alone would
    attribute the step, and hours of unrelated wall, to a frame nobody
    consumed."""
    steps: Dict[object, List[dict]] = {}
    for ev in events:
        if ev.get("ph") == "X" and ev.get("name") == "train.step":
            step = _args(ev).get("step")
            if step is not None:
                steps.setdefault(step, []).append(ev)
    for evs in steps.values():
        evs.sort(key=lambda e: e.get("ts", 0.0))
    for chain in chains.values():
        step_no = chain.get("step_no")
        anchor = chain.get("recv")
        if step_no is None or anchor is None:
            continue
        t0 = anchor.get("ts", 0.0) + anchor.get("dur", 0.0)
        for ev in steps.get(step_no, ()):
            if ev.get("ts", 0.0) >= t0 - 1.0:  # 1 µs slack
                chain["train"] = ev
                chain["pids"].add(ev.get("pid"))
                break


def _end(ev: dict) -> float:
    return ev.get("ts", 0.0) + ev.get("dur", 0.0)


def attribute(chain: dict) -> Optional[dict]:
    """One chain → ``{segments, wall_ms, coverage_pct, dominant, …}`` or
    None for a chain with no root (nothing to anchor the wall on)."""
    root = chain.get("root")
    if root is None:
        return None
    send, recv, train = (
        chain.get("send"), chain.get("recv"), chain.get("train")
    )
    last = train or recv or send or root
    wall_us = max(_end(last) - root.get("ts", 0.0), 0.0)
    seg: Dict[str, float] = {}
    decode_name = ("cache" if _args(root).get("cache_hit") else "decode")
    seg[decode_name] = root.get("dur", 0.0)
    if send is not None:
        seg["queue_wait"] = max(send.get("ts", 0.0) - _end(root), 0.0)
        if recv is not None:
            # From send START: the send span's own duration (serialize +
            # socket write) belongs to the wire segment, not to a hole.
            seg["wire"] = max(
                recv.get("ts", 0.0) - send.get("ts", 0.0), 0.0
            )
        else:
            # Sent but never merged (the peer re-striped away): the send
            # span itself is all the wire time this chain witnessed.
            seg["wire"] = send.get("dur", 0.0)
    if recv is not None:
        seg["merge"] = recv.get("dur", 0.0)
        if train is not None:
            seg["h2d"] = max(train.get("ts", 0.0) - _end(recv), 0.0)
    if train is not None:
        seg["step"] = train.get("dur", 0.0)
    attributed = sum(seg.values())
    coverage = 100.0 * attributed / wall_us if wall_us > 0 else 100.0
    segments_ms = {k: round(v / 1e3, 3) for k, v in seg.items()}
    dominant = max(seg, key=seg.get) if seg else decode_name
    return {
        "segments_ms": segments_ms,
        "wall_ms": round(wall_us / 1e3, 3),
        "coverage_pct": round(min(coverage, 100.0), 2),
        "dominant": dominant,
        "pids": sorted(p for p in chain["pids"] if p is not None),
        "step": chain.get("step_no"),
        "item": _args(root).get("item"),
    }


def analyze(events: List[dict]) -> List[dict]:
    """Merged (already rebased) events → per-batch attributions, slowest
    first."""
    chains = _chains(events)
    _join_trainer(chains, events)
    out = []
    for trace_id, chain in chains.items():
        attr = attribute(chain)
        if attr is not None:
            attr["trace_id"] = trace_id
            out.append(attr)
    out.sort(key=lambda a: a["wall_ms"], reverse=True)
    return out


# -- `ldt trace critical-path` ----------------------------------------------


def _load_costs(path: Optional[str], out) -> Dict[str, dict]:
    if not path:
        return {}
    if not os.path.exists(path):
        out.write(f"ldt trace: missing cost file {path}\n")
        return {}
    records: Dict[str, dict] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and isinstance(rec.get("key"), str):
                merged = records.setdefault(rec["key"], {})
                merged.update(
                    {k: v for k, v in rec.items() if k != "ns"}
                )
    return records


def critical_path_main(events: List[dict], out,
                       costs_path: Optional[str] = None,
                       top: int = 10) -> int:
    """Analyze merged events and print the attribution + straggler
    report (the ``ldt trace critical-path`` body — ``obs/spans.py``
    parses the arguments and loads the JSONLs)."""
    rebased, _ = rebase_events(events)
    attrs = analyze(rebased)
    if not attrs:
        out.write(
            "ldt trace: no batch chains found — record with protocol v5 "
            "peers and LDT_TRACE_PATH set on every process\n"
        )
        return 2
    total = len(attrs)
    mean_cov = sum(a["coverage_pct"] for a in attrs) / total
    dominants: Dict[str, int] = {}
    for a in attrs:
        dominants[a["dominant"]] = dominants.get(a["dominant"], 0) + 1
    out.write(
        f"ldt trace: {total} batch chains, mean coverage "
        f"{mean_cov:.1f}% of wall\n"
    )
    out.write("dominant segments: " + ", ".join(
        f"{name}={n}" for name, n in
        sorted(dominants.items(), key=lambda kv: -kv[1])
    ) + "\n")
    costs = _load_costs(costs_path, out)
    out.write(
        f"{'step':>6} {'wall_ms':>9} {'cover%':>7} {'dominant':>10} "
        "segments\n"
    )
    for a in attrs[:top]:
        segs = " ".join(
            f"{k}={v}" for k, v in sorted(a["segments_ms"].items())
        )
        out.write(
            f"{str(a['step']):>6} {a['wall_ms']:>9} "
            f"{a['coverage_pct']:>7} {a['dominant']:>10} {segs}\n"
        )
        item = a.get("item")
        if item and item in costs:
            cost = ", ".join(
                f"{k}={v}" for k, v in sorted(costs[item].items())
                if k != "key"
            )
            out.write(f"{'':>6} cost[{str(item)[:16]}]: {cost}\n")
    return 0
