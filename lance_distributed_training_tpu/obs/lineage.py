"""Batch lineage — per-batch latency attribution across process boundaries.

Every batch is stamped **at creation** (the producer that decoded it) with::

    {"batch_seq": int,     # plan step — monotonic per shard stream
     "created_ns": int,    # wall-clock epoch ns (time.time_ns) at decode end
     "decode_ms": float}   # read+decode duration (monotonic clock)

and, when it crosses the service wire, the sender adds::

    {"queue_wait_ms": float,  # time spent in the per-client bounded queue
     "sent_ns": int}          # wall-clock epoch ns at send

The consumer (``service/client.py`` / ``data/pipeline.py``) closes the loop
with :func:`observe_wire_lineage` / :func:`observe_local_lineage`, producing
``lineage_*`` / ``pipeline_*`` histograms — end-to-end latency attribution
per batch: where inside the pipeline was this batch's life spent?

Clock policy: **durations** are measured on one host with a monotonic clock
(never ``time.time()`` — LDT601); **cross-process ages** necessarily compare
wall clocks (``created_ns``/``sent_ns`` are ``time.time_ns()`` stamps), so
``wire_ms``/``batch_age_ms`` inherit inter-host clock skew — fine on the
loopback/test path, a labelled approximation across real hosts. Negative
skew clamps to 0 rather than corrupting histogram buckets. The in-process
pipeline never crosses hosts, so its age uses a monotonic twin stamp
(``created_mono_ns``, stripped before the wire) — an NTP step between
decode and pickup must not corrupt ``pipeline_batch_age_ms``.
"""

from __future__ import annotations

import math
import time
from typing import Dict, Optional

from .registry import MetricsRegistry

__all__ = [
    "make_lineage",
    "observe_wire_lineage",
    "observe_local_lineage",
]


def _as_number(value) -> Optional[float]:
    """Peer-supplied lineage values arrive as arbitrary JSON: a field that
    is not a real number is dropped (None), never raised on — a malformed
    optional-telemetry value must not kill the receive loop."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    value = float(value)
    # json.loads admits NaN/Infinity literals; one would corrupt the
    # histogram's running sum forever.
    return value if math.isfinite(value) else None


def make_lineage(batch_seq: int, decode_ms: float) -> Dict:
    """Stamp a batch at creation (the decode producer calls this)."""
    return {
        "batch_seq": int(batch_seq),
        "created_ns": time.time_ns(),
        # Monotonic twin for same-process consumers: comparable only within
        # this host/boot, so the service sender strips it before encoding.
        "created_mono_ns": time.monotonic_ns(),
        "decode_ms": round(float(decode_ms), 3),
    }


def observe_wire_lineage(
    registry: MetricsRegistry,
    lineage: Optional[Dict],
    recv_ns: Optional[int] = None,
    prefix: str = "lineage",
) -> Optional[Dict]:
    """Close the loop on a batch that crossed the service wire.

    Records ``<prefix>_batch_age_ms`` (creation → here), ``<prefix>_wire_ms``
    (send → here), and passthrough ``<prefix>_queue_wait_ms`` /
    ``<prefix>_decode_ms`` histograms. Returns the computed values (merged
    over the input) for progress lines / tests, or None for a lineage-less
    frame (an old-protocol peer) — absence is interop, not an error.

    "Here" is the receiver thread's pickup, so both ages include time a
    frame sat fully-received in the kernel socket buffer while the receiver
    was blocked handing earlier batches to a slow trainer — a wire_ms spike
    that coincides with ``svc_recv_backpressure_s`` is trainer lag, not
    network. Per-frame kernel receive timestamps would be the only way to
    split those, and are not worth a platform-specific recv path.
    """
    if not lineage:
        return None
    recv_ns = time.time_ns() if recv_ns is None else recv_ns
    out = dict(lineage)
    created = _as_number(lineage.get("created_ns"))
    if created is not None:
        age = max((recv_ns - int(created)) / 1e6, 0.0)
        out["batch_age_ms"] = round(age, 3)
        registry.histogram(f"{prefix}_batch_age_ms").observe(age)
    sent = _as_number(lineage.get("sent_ns"))
    if sent is not None:
        wire = max((recv_ns - int(sent)) / 1e6, 0.0)
        out["wire_ms"] = round(wire, 3)
        registry.histogram(f"{prefix}_wire_ms").observe(wire)
    queue_wait = _as_number(lineage.get("queue_wait_ms"))
    if queue_wait is not None:
        registry.histogram(f"{prefix}_queue_wait_ms").observe(queue_wait)
    decode = _as_number(lineage.get("decode_ms"))
    if decode is not None:
        registry.histogram(f"{prefix}_decode_ms").observe(decode)
    return out


def observe_local_lineage(
    registry: MetricsRegistry,
    lineage: Optional[Dict],
    recv_ns: Optional[int] = None,
    prefix: str = "pipeline",
) -> Optional[Dict]:
    """In-process flavour: producer and consumer share this process, so the
    age compares the monotonic twin stamp (``created_mono_ns``) — an NTP
    step between decode and pickup would corrupt a wall-clock same-host
    duration. Records ``<prefix>_batch_age_ms`` (decode end → consumer
    pickup ≈ prefetch-queue dwell) and ``<prefix>_decode_ms``. ``recv_ns``
    (tests) is a ``monotonic_ns`` instant here, unlike the wire flavour's
    wall-clock one."""
    if not lineage:
        return None
    mono = lineage.get("created_mono_ns")
    if mono is None:
        # Stamped by a producer predating the monotonic twin: wall-clock
        # attribution is the only option left. Delegate only when we'd take
        # our own "now" — a caller-supplied recv_ns here is a monotonic_ns
        # instant, which the wire flavour would misread as wall-clock.
        if recv_ns is not None:
            return None
        return observe_wire_lineage(registry, lineage, prefix=prefix)
    now = time.monotonic_ns() if recv_ns is None else recv_ns
    out = dict(lineage)
    age = max((now - int(mono)) / 1e6, 0.0)
    out["batch_age_ms"] = round(age, 3)
    registry.histogram(f"{prefix}_batch_age_ms").observe(age)
    decode = lineage.get("decode_ms")
    if decode is not None:
        registry.histogram(f"{prefix}_decode_ms").observe(float(decode))
    return out
