"""Stdlib HTTP exporter: ``/metrics`` (Prometheus text) + ``/healthz``.

One tiny ``ThreadingHTTPServer`` on a daemon thread, serving a
:class:`~.registry.MetricsRegistry` — the scrape surface for the
``DataService`` (``ldt serve-data --metrics_port``) and the trainer
(``ldt train --metrics_port``). No dependencies beyond the stdlib, no
framework: two GET routes and a 404.

* ``GET /metrics`` — Prometheus text exposition (version 0.0.4) of every
  counter/gauge/histogram in the registry.
* ``GET /healthz`` — JSON liveness: ``{"status": "ok", ...}`` merged with
  the owner's ``healthz_fn()`` extras (queue depths, client liveness, …).
  Any ``status`` other than ``"ok"`` (including a raising ``healthz_fn``,
  reported as ``"degraded"`` with the error) serves HTTP 503 so
  status-code-keyed probes can act on it — always as a fast, well-formed
  JSON body, never an unhandled 500 into a scraper's timeout path.

:func:`build_info` is the shared "what is this process" block the
``/healthz`` owners (DataService, Coordinator) merge in: package version,
spoken protocol range, which opt-in runtime sanitizers are active, and
uptime — the answer to "which build/config is the thing I'm scraping".
"""

from __future__ import annotations

import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .registry import MetricsRegistry, default_registry

__all__ = ["MetricsHTTPServer", "build_info"]

_PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Process-start anchor for uptime (module import ≈ process start for every
# CLI entry; a duration, so monotonic — LDT601).
_START_MONO = time.monotonic()


def build_info() -> dict:
    """Identify this running process: the ``/healthz`` build-info block.

    Imports are lazy (and failure-tolerant) so a scrape can never break
    on a partially-present build, and so this module keeps its
    no-service-deps posture at import time."""
    out: dict = {"uptime_s": round(time.monotonic() - _START_MONO, 1)}
    try:
        from .. import __version__

        out["version"] = __version__
    except Exception:  # noqa: BLE001 — health must not 500
        out["version"] = "unknown"
    try:
        from ..service import protocol as P

        out["protocol_versions"] = [
            P.MIN_PROTOCOL_VERSION, P.PROTOCOL_VERSION
        ]
    except Exception:  # noqa: BLE001
        pass
    sanitizers = []
    try:
        from ..utils import compiletrack, leaktrack, wiretrack

        for name, mod in (("leak", leaktrack), ("wire", wiretrack),
                          ("compile", compiletrack)):
            if mod.enabled():
                sanitizers.append(name)
    except Exception:  # noqa: BLE001
        pass
    out["sanitizers_active"] = sanitizers
    return out


class MetricsHTTPServer:
    """Serve a registry over HTTP until :meth:`stop`.

    ``port=0`` binds an ephemeral port (the bound one is ``self.port`` after
    :meth:`start` — tests and the CI smoke use this). ``host`` defaults to
    loopback: ``/healthz`` exposes dataset paths, peer addresses, and
    cursors with no auth, so serving beyond the host is an explicit opt-in
    (``--metrics_host 0.0.0.0`` on a fleet box behind its scrape network).
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        port: int = 0,
        host: str = "127.0.0.1",
        healthz_fn: Optional[Callable[[], dict]] = None,
    ):
        self.registry = registry if registry is not None else default_registry()
        self.host = host
        self.requested_port = port
        self.healthz_fn = healthz_fn
        self.port: Optional[int] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsHTTPServer":
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # scrapes are not news
                pass

            def _respond(self, status: int, content_type: str,
                         body: bytes) -> None:
                try:
                    self.send_response(status)
                    self.send_header("Content-Type", content_type)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    # Scrape timeout aborted the connection mid-write: the
                    # scraper is gone, a per-interval stderr traceback
                    # (socketserver's default handle_error) is just noise.
                    self.close_connection = True

            def do_GET(self) -> None:
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = exporter.registry.render_prometheus().encode()
                    self._respond(200, _PROM_CONTENT_TYPE, body)
                elif path == "/healthz":
                    payload = {"status": "ok"}
                    if exporter.healthz_fn is not None:
                        try:
                            payload.update(exporter.healthz_fn())
                        except Exception as exc:  # health must not 500
                            payload = {"status": "degraded",
                                       "error": repr(exc)}
                    # Status-code-keyed probes (k8s httpGet, LB checks) need
                    # a non-2xx to act on; 503 is still a fast, well-formed
                    # response — only an unhandled exception could hang a
                    # scraper, and that path is caught above.
                    status = 200 if payload.get("status") == "ok" else 503
                    self._respond(
                        status, "application/json",
                        json.dumps(payload).encode(),
                    )
                else:
                    self._respond(404, "text/plain", b"not found\n")

        class Server(ThreadingHTTPServer):
            daemon_threads = True  # a slow scraper never pins exit

            def handle_error(self, request, client_address) -> None:
                # Covers the disconnect raised at finish()/flush time, past
                # _respond's own guard — same rationale.
                exc = sys.exc_info()[1]
                if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
                    return
                super().handle_error(request, client_address)

        self._httpd = Server((self.host, self.requested_port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="ldt-metrics-http",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "MetricsHTTPServer":
        return self.start() if self._httpd is None else self

    def __exit__(self, *exc) -> None:
        self.stop()
