"""Observability: metrics registry, span tracing, batch lineage, exporters.

The telemetry layer the BASELINE north-star metric ("<2% of step time
blocked on the loader") needs once the pipeline is disaggregated: ad-hoc
counters can say *that* a stall happened, only end-to-end attribution can
say *where it was born* — fragment read vs decode vs queue vs wire vs H2D.

* :mod:`.registry` — thread-safe counters / gauges / fixed-bucket
  histograms (p50/p95/p99 by bucket interpolation, bounded memory), one
  process-wide :func:`~.registry.default_registry` every layer meets in;
* :mod:`.spans` — monotonic-clock span tracer (ring buffer, parent ids)
  with Chrome-trace/Perfetto export (``ldt trace export``) and
  ``jax.profiler.TraceAnnotation`` passthrough;
* :mod:`.lineage` — per-batch ``(batch_seq, created_ns, stage_timings)``
  stamps carried through the data plane (and the service wire, versioned +
  backward compatible), closed into ``batch_age_ms``/``wire_ms``/
  ``queue_wait_ms``/``decode_ms`` histograms at the consumer;
* :mod:`.http` — stdlib ``/metrics`` (Prometheus text) + ``/healthz``
  exporter (``--metrics_port`` on ``serve-data`` and ``train``).

Deliberately dependency-free (stdlib only; jax is optional) so decode-only
service hosts carry the same telemetry as trainers.

Robustness series (r8, recorded by ``utils/checkpoint.py`` /
``utils/signals.py`` / ``utils/retry.py`` into the default registry):

* ``ckpt_save_ms`` — histogram of checkpoint save dispatch (+ commit wait
  for awaited emergency saves);
* ``ckpt_last_success_step`` — gauge: the newest persisted absolute step
  (stale vs ``trainer_step_ms_count`` = the save plane is wedged);
* ``trainer_preemptions_total`` — counter: SIGTERM (or chaos) drains;
* ``retry_attempts_total`` — counter: reconnect retries across ALL
  subsystems (client connects, fleet resolves/dials) after unification in
  ``utils/retry.py``.

Autotune series (r9, recorded by ``tune/`` into the default registry):
``autotune_ticks_total`` / ``autotune_decisions_total`` /
``autotune_reverts_total`` counters, ``autotune_knob_<name>`` gauges, and
``autotune_bottleneck`` (coded attribution — README "Autotune"); the fleet
half adds ``fleet_pressure_stall_pct_max``/``_mean`` and
``fleet_scale_recommendation`` on the coordinator. :class:`RegistryDelta`
is the windowed view the controller (and bench scripts) read — deltas
since the previous call, histogram percentiles over the window's own
bucket increments.

Batch-cache series (r13, recorded by ``data/cache.py`` into the default
registry — README "Batch cache" for the full glossary):
``cache_hit_total`` / ``cache_miss_total`` / ``cache_disk_hit_total`` /
``cache_store_total`` / ``cache_spill_total`` / ``cache_evict_total`` /
``cache_torn_total`` / ``cache_spill_errors_total`` counters, the
``cache_ram_bytes`` / ``cache_disk_bytes`` / ``cache_ram_entries`` /
``cache_disk_entries`` occupancy gauges, the ``cache_lookup_ms``
histogram, and the HBM replay tier's ``cache_device_batches`` gauge +
``cache_device_replay_epochs_total`` counter.

Ragged-token series (r15, recorded by ``data/token_pack.py`` /
``ops/token_device.py`` — README "Ragged token plane" for the full
glossary): ``pack_payload_tokens_total`` vs ``pack_grid_tokens_total``
(real vs processed tokens; their window ratio is ``pad_waste_pct`` /
``pack_occupancy`` in the autotune signal dict — emitted by the padded
control arm too, so the waste cut is measured, not assumed),
``pack_sequences_total`` / ``pack_batches_total`` /
``pack_truncated_tokens_total`` counters, ``pack_new_shapes_total``
(fresh pack-kernel jit traces — the recompile cost the
``pack_rows_quantum`` policy rung trades against waste), the sampled
``pack_device_ms`` histogram, and the buffer plane's
``bufpool_ragged_leases_total`` / ``bufpool_ragged_slack_bytes_total``
(capacity-bucket overhead). ``decode_token_bytes_total`` /
``decode_token_copies_total`` are the token path's LDT701 copy-hygiene
rows: bytes leaving decode vs bytes that could not take the zero-copy
Arrow view.

Protocol series (r14 — README "Protocol"):

* ``svc_proto_malformed_hello`` — counter: HELLOs rejected at the type
  gate (``protocol.hello_malformed``) with a skew-style MSG_ERROR — a
  mixed-version or corrupted peer sending a wrong-typed field, answered
  diagnosably instead of a handler-killing ValueError;
* ``fleet_leave_generation`` — gauge: the lease-table generation a
  member's graceful deregister produced (its last fleet fact);
* the opt-in wire witness (``LDT_WIRE_SANITIZER=1``,
  ``utils/wiretrack.py``) records off-registry — per-(msg, field) wire
  counts feed ``ldt check --wire-witness``, not ``/metrics``.

Causal-tracing & SLO series (r18 — README "Causal tracing & SLOs"):

* :mod:`.tracectx` — W3C-style ``(trace_id, parent_span_id)`` context
  stamped at plan-item decode, riding the protocol-v5 batch meta so one
  batch's decode → send → merge → step chain reconstructs across
  processes (``ldt trace export`` draws the parent edges);
* :mod:`.costs` — per-item cost ledger (ring-buffered; ``LDT_COST_PATH``
  JSONL; ``ldt costs report``) keyed by the BatchCache content hash:
  ``cost_records_total`` / ``cost_bytes_total`` / ``cost_reencode_total``
  counters plus ``cost_decode_ms`` / ``cost_entropy_ms`` /
  ``cost_token_len`` histograms;
* :mod:`.critpath` — per-batch dominant-segment attribution + straggler
  table (``ldt trace critical-path``; per-epoch summary in the trainer's
  ``critpath_*`` metrics);
* :mod:`.slo` — declared SLOs (``LDT_SLOS``) with multi-window burn-rate
  gauges: ``slo_<name>`` + ``slo_<name>_burn_<window>`` on ``/metrics``,
  ``slo`` block on ``/healthz``; the fleet half aggregates member
  heartbeat histograms into ``fleet_queue_wait_p50/p95/p99_ms``;
* ``spans_dropped_total`` — counter: spans evicted from a full tracer
  ring (the export prints the merged dropped count).
"""

from .http import MetricsHTTPServer, build_info  # noqa: F401
from .lineage import (  # noqa: F401
    make_lineage,
    observe_local_lineage,
    observe_wire_lineage,
)
from .registry import (  # noqa: F401
    DEFAULT_MS_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    RegistryDelta,
    default_registry,
    percentile_from_counts,
    render_prometheus,
)
from .costs import (  # noqa: F401
    CostLedger,
    cost_context,
    default_ledger,
    note_cost,
)
from .critpath import analyze as critpath_analyze  # noqa: F401
from .slo import SLO, DEFAULT_SLOS, SLOTracker, parse_slos  # noqa: F401
from .spans import (  # noqa: F401
    Span,
    SpanTracer,
    chrome_trace,
    default_tracer,
    span,
)
from .tracectx import (  # noqa: F401
    child,
    coerce_trace,
    make_trace,
    new_span_id,
    new_trace_id,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsHTTPServer",
    "RegistryDelta",
    "DEFAULT_MS_BUCKETS",
    "default_registry",
    "percentile_from_counts",
    "render_prometheus",
    "Span",
    "SpanTracer",
    "chrome_trace",
    "default_tracer",
    "span",
    "make_lineage",
    "observe_wire_lineage",
    "observe_local_lineage",
    "build_info",
    "CostLedger",
    "cost_context",
    "default_ledger",
    "note_cost",
    "critpath_analyze",
    "SLO",
    "DEFAULT_SLOS",
    "SLOTracker",
    "parse_slos",
    "child",
    "coerce_trace",
    "make_trace",
    "new_span_id",
    "new_trace_id",
]
