"""Lightweight span tracing with a Chrome-trace / Perfetto export surface.

A :class:`SpanTracer` records named regions (monotonic-clock start/stop,
parent ids from a per-thread stack) into a bounded ring buffer — the
always-on, ~zero-cost sibling of ``jax.profiler`` traces. Three export
surfaces:

* **Perfetto / chrome://tracing** — :func:`chrome_trace` converts completed
  spans to Chrome trace-event JSON (``ph: "X"`` complete events), written by
  :meth:`SpanTracer.write_chrome_trace` or the ``ldt trace export`` CLI
  (:func:`trace_main`).
* **XPlane passthrough** — every span also enters a
  ``jax.profiler.TraceAnnotation`` when jax is importable, so the same
  regions appear on the host timeline of a ``jax.profiler`` trace
  (``utils/profiling.trace``). No-op (and no jax import cost) otherwise.
* **cross-process JSONL** — set ``LDT_TRACE_PATH`` (or pass ``jsonl_path``)
  and completed spans append to a JSONL file one event per line; ``ldt
  trace export --spans that-file`` stitches any number of processes'
  files into one Perfetto-loadable trace.

Clocks: span durations come from ``time.monotonic_ns`` (LDT601 forbids
``time.time()`` here); the JSONL/export timestamps are the same monotonic
microseconds, which Perfetto renders relative — absolute wall alignment
across hosts is the lineage layer's job, not the tracer's.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Iterator, List, Optional

__all__ = [
    "Span",
    "SpanTracer",
    "default_tracer",
    "span",
    "chrome_trace",
    "trace_main",
]


@dataclasses.dataclass(frozen=True)
class Span:
    """One completed region. Times are ``time.monotonic_ns()`` instants."""

    name: str
    start_ns: int
    end_ns: int
    span_id: int
    parent_id: int  # 0 = root
    thread_id: int
    pid: int
    attrs: Optional[dict] = None

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def to_event(self) -> dict:
        """Chrome trace-event dict (``ph: "X"`` complete event; ts/dur in
        microseconds — the Perfetto/chrome://tracing contract)."""
        args = {"span_id": self.span_id, "parent_id": self.parent_id}
        if self.attrs:
            args.update(self.attrs)
        return {
            "name": self.name,
            "ph": "X",
            "ts": self.start_ns / 1e3,
            "dur": (self.end_ns - self.start_ns) / 1e3,
            "pid": self.pid,
            "tid": self.thread_id,
            "args": args,
        }


def _annotation(name: str):
    """``jax.profiler.TraceAnnotation`` when jax is importable, else None —
    the tracer must work in decode-only processes without jax installed."""
    global _ANNOTATION_CLS
    if _ANNOTATION_CLS is False:
        return None
    if _ANNOTATION_CLS is None:
        try:
            import jax

            _ANNOTATION_CLS = jax.profiler.TraceAnnotation
        except Exception:  # jax absent/broken: tracer still works
            _ANNOTATION_CLS = False
            return None
    return _ANNOTATION_CLS(name)


_ANNOTATION_CLS = None  # unresolved | False (unavailable) | the class


class SpanTracer:
    """Thread-safe tracer: a ring buffer of completed spans.

    ``capacity`` bounds memory forever (old spans fall off the back — the
    recent-window view an engineer actually opens). ``jsonl_path`` (or the
    ``LDT_TRACE_PATH`` env var) additionally appends every completed span as
    one JSON line, the durable form ``ldt trace export`` consumes.
    """

    def __init__(self, capacity: int = 4096,
                 jsonl_path: Optional[str] = None):
        self._lock = threading.Lock()  # ring buffer only — never held for IO
        self._io_lock = threading.Lock()  # JSONL handle; a slow flush must
        # not block threads opening spans or appending to the ring
        self._spans: deque = deque(maxlen=max(1, capacity))
        self._local = threading.local()
        self._ids = itertools.count(1)  # GIL-atomic: id allocation is lockless
        self._jsonl = None
        self._jsonl_path = jsonl_path or os.environ.get("LDT_TRACE_PATH")

    # -- recording ---------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        """Record the enclosed block as one span; nests (parent = innermost
        open span on this thread) and mirrors into the jax profiler's host
        timeline when a profiler trace is active."""
        stack = self._stack()
        span_id = next(self._ids)
        parent_id = stack[-1] if stack else 0
        stack.append(span_id)
        annotation = _annotation(name)
        start = time.monotonic_ns()
        try:
            if annotation is not None:
                with annotation:
                    yield
            else:
                yield
        finally:
            end = time.monotonic_ns()
            stack.pop()
            self._record(Span(
                name=name, start_ns=start, end_ns=end, span_id=span_id,
                parent_id=parent_id, thread_id=threading.get_ident() % 2**31,
                pid=os.getpid(), attrs=attrs or None,
            ))

    def _record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
        if self._jsonl_path is None:
            return
        # Serialize + flush outside the ring lock: a stalled disk slows the
        # writer, not every thread opening a span. Flush-per-span is the
        # durability contract (`ldt trace export` must see spans from
        # processes that died mid-run).
        line = json.dumps(span.to_event()) + "\n"
        with self._io_lock:
            if self._jsonl_path is None:
                return
            if self._jsonl is None:
                try:
                    self._jsonl = open(self._jsonl_path, "a")
                except OSError:
                    self._jsonl_path = None  # never retry a bad path
                    return
            self._jsonl.write(line)
            self._jsonl.flush()

    # -- reading / export --------------------------------------------------

    def spans(self) -> List[Span]:
        """Completed spans, oldest first (bounded by ``capacity``)."""
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def chrome_trace(self) -> dict:
        return chrome_trace([s.to_event() for s in self.spans()])

    def write_chrome_trace(self, path: str) -> str:
        """Dump the ring buffer as a Perfetto-loadable JSON file."""
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
            f.write("\n")
        return path

    def close(self) -> None:
        """Terminal: spans completing after close (e.g. on a daemon thread
        racing shutdown) still enter the ring buffer but no longer reopen
        the JSONL file."""
        with self._io_lock:
            self._jsonl_path = None
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None


def chrome_trace(events: List[dict]) -> dict:
    """Wrap trace events in the Chrome trace-event JSON envelope."""
    return {
        "traceEvents": list(events),
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "ldt trace export"},
    }


_DEFAULT: Optional[SpanTracer] = None
_DEFAULT_LOCK = threading.Lock()


def default_tracer() -> SpanTracer:
    """The process-wide tracer every instrumented layer records into.
    Created lazily so ``LDT_TRACE_PATH`` set by the entry point (CLI, test)
    is read at first use, not at import."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = SpanTracer()
        return _DEFAULT


def span(name: str, **attrs):
    """Record a region on the process-wide tracer — the one-liner the
    instrumented modules use: ``with span("svc.decode", step=n): …``."""
    return default_tracer().span(name, **attrs)


# -- `ldt trace` CLI ---------------------------------------------------------


def trace_main(argv=None, out=None) -> int:
    """``ldt trace export`` — convert recorded span JSONL (written by any
    process running with ``LDT_TRACE_PATH``) into one Chrome-trace JSON
    loadable in Perfetto (https://ui.perfetto.dev) or chrome://tracing.
    Returns the process exit status."""
    import argparse
    import sys

    out = out if out is not None else sys.stdout
    p = argparse.ArgumentParser(
        prog="ldt trace",
        description="Export recorded spans as a Perfetto-loadable "
                    "Chrome-trace JSON",
    )
    sub = p.add_subparsers(dest="command")
    exp = sub.add_parser("export", help="convert span JSONL → Chrome trace")
    exp.add_argument(
        "--spans", action="append", default=None, metavar="JSONL",
        help="span JSONL file(s) written under LDT_TRACE_PATH (repeatable; "
             "default: $LDT_TRACE_PATH or ldt-spans.jsonl)",
    )
    exp.add_argument("--out", default="ldt-trace.json",
                     help="output Chrome-trace JSON path")
    args = p.parse_args(list(argv) if argv is not None else None)
    if args.command != "export":
        p.print_help(out)
        return 2
    spans_paths = args.spans or [
        os.environ.get("LDT_TRACE_PATH", "ldt-spans.jsonl")
    ]
    events: List[dict] = []
    missing = []
    for path in spans_paths:
        if not os.path.exists(path):
            missing.append(path)
            continue
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, start=1):
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    out.write(
                        f"ldt trace: skipping undecodable line "
                        f"{path}:{lineno}\n"
                    )
    if missing:
        # A partial multi-process merge must say so: a silently dropped
        # host's spans read as "that host did nothing" in Perfetto.
        out.write(
            f"ldt trace: missing span file(s): {', '.join(missing)}\n"
        )
        if not events:
            out.write(
                "ldt trace: no events collected — run with "
                "LDT_TRACE_PATH=<file> to record spans\n"
            )
            return 2
    with open(args.out, "w") as f:
        json.dump(chrome_trace(events), f)
        f.write("\n")
    out.write(
        f"ldt trace: wrote {len(events)} events to {args.out} — open it at "
        "https://ui.perfetto.dev or chrome://tracing\n"
    )
    return 0
