"""Lightweight span tracing with a Chrome-trace / Perfetto export surface.

A :class:`SpanTracer` records named regions (monotonic-clock start/stop,
parent ids from a per-thread stack) into a bounded ring buffer — the
always-on, ~zero-cost sibling of ``jax.profiler`` traces. Three export
surfaces:

* **Perfetto / chrome://tracing** — :func:`chrome_trace` converts completed
  spans to Chrome trace-event JSON (``ph: "X"`` complete events), written by
  :meth:`SpanTracer.write_chrome_trace` or the ``ldt trace export`` CLI
  (:func:`trace_main`).
* **XPlane passthrough** — every span also enters a
  ``jax.profiler.TraceAnnotation`` when jax is importable, so the same
  regions appear on the host timeline of a ``jax.profiler`` trace
  (``utils/profiling.trace``). No-op (and no jax import cost) otherwise.
* **cross-process JSONL** — set ``LDT_TRACE_PATH`` (or pass ``jsonl_path``)
  and completed spans append to a JSONL file one event per line; ``ldt
  trace export --spans that-file`` stitches any number of processes'
  files into one Perfetto-loadable trace.

Clocks: span durations come from ``time.monotonic_ns`` (LDT601 forbids
``time.time()`` here); the JSONL/export timestamps are the same monotonic
microseconds. For CROSS-process merge each JSONL additionally carries one
``ldt.clock_sync`` anchor record (``wall_ns`` + ``mono_ns`` captured
together — an epoch *stamp* that intentionally crosses process
boundaries, the lineage clock policy) so ``ldt trace export`` can rebase
every process onto one wall timeline; within a process all math stays
monotonic.

Ring-buffer truncation is observable, not silent: every span dropped off
the full ring increments the ``spans_dropped_total`` counter, and JSONL
files carry cumulative ``ldt.spans_dropped`` markers so ``ldt trace
export`` can report how much the source processes truncated.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Iterator, List, Optional

__all__ = [
    "Span",
    "SpanTracer",
    "default_tracer",
    "span",
    "chrome_trace",
    "trace_main",
]


@dataclasses.dataclass(frozen=True)
class Span:
    """One completed region. Times are ``time.monotonic_ns()`` instants."""

    name: str
    start_ns: int
    end_ns: int
    span_id: int
    parent_id: int  # 0 = root
    thread_id: int
    pid: int
    attrs: Optional[dict] = None

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def to_event(self) -> dict:
        """Chrome trace-event dict (``ph: "X"`` complete event; ts/dur in
        microseconds — the Perfetto/chrome://tracing contract)."""
        args = {"span_id": self.span_id, "parent_id": self.parent_id}
        if self.attrs:
            args.update(self.attrs)
        return {
            "name": self.name,
            "ph": "X",
            "ts": self.start_ns / 1e3,
            "dur": (self.end_ns - self.start_ns) / 1e3,
            "pid": self.pid,
            "tid": self.thread_id,
            "args": args,
        }


def _annotation(name: str):
    """``jax.profiler.TraceAnnotation`` when jax is importable, else None —
    the tracer must work in decode-only processes without jax installed."""
    global _ANNOTATION_CLS
    if _ANNOTATION_CLS is False:
        return None
    if _ANNOTATION_CLS is None:
        try:
            import jax

            _ANNOTATION_CLS = jax.profiler.TraceAnnotation
        except Exception:  # jax absent/broken: tracer still works
            _ANNOTATION_CLS = False
            return None
    return _ANNOTATION_CLS(name)


_ANNOTATION_CLS = None  # unresolved | False (unavailable) | the class


class SpanTracer:
    """Thread-safe tracer: a ring buffer of completed spans.

    ``capacity`` bounds memory forever (old spans fall off the back — the
    recent-window view an engineer actually opens). ``jsonl_path`` (or the
    ``LDT_TRACE_PATH`` env var) additionally appends every completed span as
    one JSON line, the durable form ``ldt trace export`` consumes.
    """

    def __init__(self, capacity: int = 4096,
                 jsonl_path: Optional[str] = None):
        self._lock = threading.Lock()  # ring buffer only — never held for IO
        self._io_lock = threading.Lock()  # JSONL handle; a slow flush must
        # not block threads opening spans or appending to the ring
        self._spans: deque = deque(maxlen=max(1, capacity))
        self._local = threading.local()
        self._ids = itertools.count(1)  # GIL-atomic: id allocation is lockless
        self._jsonl = None
        self._jsonl_path = jsonl_path or os.environ.get("LDT_TRACE_PATH")
        self._dropped = 0  # spans pushed off the full ring (see dropped)

    # -- recording ---------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @contextmanager
    def span(self, name: str, **attrs) -> Iterator[dict]:
        """Record the enclosed block as one span; nests (parent = innermost
        open span on this thread) and mirrors into the jax profiler's host
        timeline when a profiler trace is active.

        Yields the span's attrs dict so attributes only known mid-block
        (``cache_hit``, result sizes) can be added before the span
        closes: ``with span("x") as a: a["hit"] = True``."""
        stack = self._stack()
        span_id = next(self._ids)
        parent_id = stack[-1] if stack else 0
        stack.append(span_id)
        annotation = _annotation(name)
        start = time.monotonic_ns()
        try:
            if annotation is not None:
                with annotation:
                    yield attrs
            else:
                yield attrs
        finally:
            end = time.monotonic_ns()
            stack.pop()
            self._record(Span(
                name=name, start_ns=start, end_ns=end, span_id=span_id,
                parent_id=parent_id, thread_id=threading.get_ident() % 2**31,
                pid=os.getpid(), attrs=attrs or None,
            ))

    def _record(self, span: Span) -> None:
        dropped = 0
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                # The ring evicts the oldest span to admit this one —
                # count it so a truncated in-process trace is diagnosable
                # (the old behavior dropped silently).
                self._dropped += 1
                dropped = self._dropped
            self._spans.append(span)
        if dropped:
            # Lazy import: registry never imports spans, so no cycle.
            from .registry import default_registry

            default_registry().counter("spans_dropped_total").inc()
        if self._jsonl_path is None:
            return
        # Serialize + flush outside the ring lock: a stalled disk slows the
        # writer, not every thread opening a span. Flush-per-span is the
        # durability contract (`ldt trace export` must see spans from
        # processes that died mid-run).
        line = json.dumps(span.to_event()) + "\n"
        if dropped and (dropped & (dropped - 1)) == 0:
            # Cumulative drop marker at power-of-two counts: the ring in
            # steady-state overflow drops one span per record, so a
            # per-drop marker would double the file; doubling cadence
            # keeps the count accurate within 2x at O(log n) lines.
            line += json.dumps({
                "name": "ldt.spans_dropped", "ph": "C",
                "pid": span.pid, "tid": 0,
                "ts": span.end_ns / 1e3,
                "args": {"dropped": dropped},
            }) + "\n"
        with self._io_lock:
            if self._jsonl_path is None:
                return
            if self._jsonl is None:
                try:
                    self._jsonl = open(self._jsonl_path, "a")
                except OSError:
                    self._jsonl_path = None  # never retry a bad path
                    return
                # One wall/monotonic anchor pair per (process, open):
                # what lets `ldt trace export` place this process's
                # monotonic timestamps on the shared wall timeline. An
                # epoch stamp crossing processes — the LDT601-sanctioned
                # use (see obs/lineage.py's clock policy).
                self._jsonl.write(json.dumps({
                    "name": "ldt.clock_sync", "ph": "M",
                    "pid": os.getpid(), "tid": 0, "ts": 0,
                    "args": {
                        "wall_ns": time.time_ns(),
                        "mono_ns": time.monotonic_ns(),
                    },
                }) + "\n")
            self._jsonl.write(line)
            self._jsonl.flush()

    # -- reading / export --------------------------------------------------

    def spans(self) -> List[Span]:
        """Completed spans, oldest first (bounded by ``capacity``)."""
        with self._lock:
            return list(self._spans)

    @property
    def dropped(self) -> int:
        """Spans pushed off the full ring since construction."""
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def chrome_trace(self) -> dict:
        out = chrome_trace([s.to_event() for s in self.spans()])
        dropped = self.dropped
        if dropped:
            out["otherData"]["spans_dropped"] = dropped
        return out

    def write_chrome_trace(self, path: str) -> str:
        """Dump the ring buffer as a Perfetto-loadable JSON file."""
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
            f.write("\n")
        return path

    def close(self) -> None:
        """Terminal: spans completing after close (e.g. on a daemon thread
        racing shutdown) still enter the ring buffer but no longer reopen
        the JSONL file."""
        with self._io_lock:
            self._jsonl_path = None
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None


def chrome_trace(events: List[dict]) -> dict:
    """Wrap trace events in the Chrome trace-event JSON envelope."""
    return {
        "traceEvents": list(events),
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "ldt trace export"},
    }


_DEFAULT: Optional[SpanTracer] = None
_DEFAULT_LOCK = threading.Lock()


def default_tracer() -> SpanTracer:
    """The process-wide tracer every instrumented layer records into.
    Created lazily so ``LDT_TRACE_PATH`` set by the entry point (CLI, test)
    is read at first use, not at import."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = SpanTracer()
        return _DEFAULT


def span(name: str, **attrs):
    """Record a region on the process-wide tracer — the one-liner the
    instrumented modules use: ``with span("svc.decode", step=n): …``."""
    return default_tracer().span(name, **attrs)


# -- `ldt trace` CLI ---------------------------------------------------------


def _load_span_events(paths: List[str], out) -> List[dict]:
    """Merge span JSONL files into one event list (undecodable lines are
    reported and skipped; missing files are reported — a silently dropped
    host's spans read as "that host did nothing" in Perfetto)."""
    events: List[dict] = []
    missing = []
    for path in paths:
        if not os.path.exists(path):
            missing.append(path)
            continue
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, start=1):
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    out.write(
                        f"ldt trace: skipping undecodable line "
                        f"{path}:{lineno}\n"
                    )
    if missing:
        out.write(
            f"ldt trace: missing span file(s): {', '.join(missing)}\n"
        )
    return events


def trace_main(argv=None, out=None) -> int:
    """``ldt trace export`` / ``ldt trace critical-path``.

    * ``export`` merges span JSONLs (written by any process running with
      ``LDT_TRACE_PATH``) into ONE Perfetto-loadable Chrome-trace JSON:
      per-process clocks rebased onto the wall timeline via the
      ``ldt.clock_sync`` anchors, cross-process batch chains stitched
      with flow arrows (``obs/critpath.py``), ring-buffer drop counts
      reported.
    * ``critical-path`` analyzes the same merged events into per-batch
      segment attribution + a straggler table.

    Returns the process exit status."""
    import argparse
    import sys

    out = out if out is not None else sys.stdout
    p = argparse.ArgumentParser(
        prog="ldt trace",
        description="Merge and analyze recorded span JSONLs",
    )
    sub = p.add_subparsers(dest="command")
    exp = sub.add_parser("export", help="convert span JSONL → Chrome trace")
    cp = sub.add_parser(
        "critical-path",
        help="per-batch segment attribution + straggler table",
    )
    for sp in (exp, cp):
        sp.add_argument(
            "--spans", action="append", default=None, metavar="JSONL",
            help="span JSONL file(s) written under LDT_TRACE_PATH "
                 "(repeatable; default: $LDT_TRACE_PATH or "
                 "ldt-spans.jsonl)",
        )
    exp.add_argument("--out", default="ldt-trace.json",
                     help="output Chrome-trace JSON path")
    cp.add_argument("--costs", default=None, metavar="JSONL",
                    help="cost-ledger JSONL (LDT_COST_PATH) to join the "
                         "straggler table against")
    cp.add_argument("--top", type=int, default=10,
                    help="slowest chains to show (default 10)")
    args = p.parse_args(list(argv) if argv is not None else None)
    if args.command not in ("export", "critical-path"):
        p.print_help(out)
        return 2
    from .critpath import (
        critical_path_main,
        dropped_spans,
        flow_events,
        rebase_events,
    )

    spans_paths = args.spans or [
        os.environ.get("LDT_TRACE_PATH", "ldt-spans.jsonl")
    ]
    events = _load_span_events(spans_paths, out)
    if not events:
        out.write(
            "ldt trace: no events collected — run with "
            "LDT_TRACE_PATH=<file> to record spans\n"
        )
        return 2
    if args.command == "critical-path":
        return critical_path_main(events, out, costs_path=args.costs,
                                  top=args.top)
    rebased, offsets = rebase_events(events)
    flows = flow_events(rebased)
    dropped = dropped_spans(events)
    with open(args.out, "w") as f:
        json.dump(chrome_trace(rebased + flows), f)
        f.write("\n")
    out.write(
        f"ldt trace: wrote {len(rebased)} events (+{len(flows)} flow "
        f"arrows, {len(offsets)} process clocks aligned) to {args.out} — "
        "open it at https://ui.perfetto.dev or chrome://tracing\n"
    )
    if dropped:
        out.write(
            f"ldt trace: source ring buffers dropped ~{dropped} spans — "
            "the merged trace is truncated (raise SpanTracer capacity "
            "or rely on the JSONL, which never drops)\n"
        )
    return 0
