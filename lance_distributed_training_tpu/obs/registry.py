"""Thread-safe metrics registry — counters, gauges, fixed-bucket histograms.

The telemetry substrate every instrumented layer records into:
``ServiceCounters`` and ``StepTimer`` (``utils/metrics.py``) are thin facades
over it, the data pipeline and the disaggregated service observe per-batch
latency histograms through it, and ``obs/http.py`` renders it as Prometheus
text for scraping.

Design constraints, in order:

* **bounded memory** — histograms are fixed-bucket (no reservoirs, no raw
  sample retention): percentiles come from linear interpolation inside the
  bucket containing the target rank, the same estimate Prometheus'
  ``histogram_quantile`` computes server-side. A histogram is ~20 floats
  forever, no matter how many observations land in it.
* **thread-safe hot path** — every metric guards its state with its own
  small lock; ``observe``/``inc`` are a bisect + two adds, cheap enough to
  sit on per-batch paths.
* **one process-wide registry** — :func:`default_registry` is where all
  layers meet, so one ``/metrics`` endpoint sees the whole process (server
  counters AND client lineage histograms in a loopback test). Instances are
  still constructible for isolation (tests, multiple exporters).

Metric names must match ``[a-z][a-z0-9_]*`` (enforced here and by the
LDT601 lint) so every name is a valid Prometheus metric name as-is.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

__all__ = [
    "METRIC_NAME_RE",
    "DEFAULT_MS_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "RegistryDelta",
    "default_registry",
    "percentile_from_counts",
    "render_prometheus",
]

METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# Default latency buckets (milliseconds): sub-ms decode through multi-second
# stalls. 16 finite bounds + the implicit +Inf overflow bucket.
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)


def _check_name(name: str) -> str:
    if not METRIC_NAME_RE.match(name):
        raise ValueError(
            f"invalid metric name {name!r}: must match [a-z][a-z0-9_]* "
            "(a Prometheus-safe lower_snake_case name)"
        )
    return name


def percentile_from_counts(
    bounds: Sequence[float],
    counts: Sequence[int],
    total: int,
    q: float,
    observed_max: float = math.nan,
) -> float:
    """q-th percentile (0 < q <= 100) of a fixed-bucket count vector by
    linear interpolation — the shared math behind
    :meth:`Histogram.percentile` and the windowed-delta view
    (:class:`RegistryDelta`), where ``counts`` is a *difference* of two
    cumulative snapshots. A rank landing in the +Inf bucket clamps to
    ``observed_max`` when known (lifetime histograms track it) or the top
    finite bound (delta windows, which have no per-window max). NaN when
    ``total`` is 0."""
    if total <= 0:
        return math.nan
    # Fractional rank, no ceil — matches Prometheus histogram_quantile
    # (one observation in (1, 10] gives p50 = 5.5, not the bucket top).
    rank = total * min(max(q, 0.0), 100.0) / 100.0
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= rank:
            if i >= len(bounds):  # overflow bucket
                if not math.isnan(observed_max):
                    return max(bounds[-1], observed_max)
                return bounds[-1]
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - cum) / c
            return lo + (hi - lo) * frac
        cum += c
    return bounds[-1]


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render without the noise of
    a mantissa (``17`` not ``17.0``), everything else as repr."""
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Counter:
    """Monotonically-increasing sum. ``inc(v)`` with v >= 0."""

    kind = "counter"

    def __init__(self, name: str):
        self.name = _check_name(name)
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, value: float = 1.0) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Instantaneous value. ``set(v)``."""

    kind = "gauge"

    def __init__(self, name: str):
        self.name = _check_name(name)
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with interpolated percentiles.

    ``buckets`` are the finite upper bounds (ascending); an implicit +Inf
    bucket catches overflow. Cumulative-bucket semantics match Prometheus:
    ``_bucket{le="b"}`` counts observations <= b.
    """

    kind = "histogram"

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_MS_BUCKETS):
        self.name = _check_name(name)
        bounds = tuple(float(b) for b in buckets)
        if not bounds or any(
            b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])
        ):
            raise ValueError(
                f"histogram {name} buckets must be non-empty and strictly "
                f"ascending, got {bounds}"
            )
        self.bounds = bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)  # last slot = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._max = math.nan  # largest observation: the +Inf-bucket clamp

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if not value <= self._max:  # first observe: nan comparison
                self._max = value

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> Tuple[list, float, int]:
        """``(per-bucket counts incl. +Inf, sum, count)`` — one consistent
        read for rendering and percentile math."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def percentile(self, q: float) -> float:
        """Estimate the q-th percentile (0 < q <= 100) by linear
        interpolation inside the bucket holding the target rank — bounded
        error (one bucket width), zero sample retention. A rank landing in
        the +Inf bucket clamps to the largest observation seen (not the top
        finite bound, which would understate a 60 s stall as 10 s — exactly
        the tail these histograms exist to surface). Returns NaN when
        empty."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            observed_max = self._max
        return percentile_from_counts(
            self.bounds, counts, total, q, observed_max
        )

    def percentiles(self, qs: Iterable[float] = (50, 95, 99)) -> Dict[str, float]:
        """``{"p50": …, "p95": …, "p99": …}`` for the given quantiles."""
        return {f"p{int(q)}": self.percentile(q) for q in qs}


class MetricsRegistry:
    """Get-or-create registry of named metrics.

    Re-requesting an existing name returns the same object (so independent
    layers aggregate into one series, Prometheus-style); requesting it as a
    different kind is an error — silent type morphing would corrupt the
    scrape output.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, name: str, factory, kind: str):
        # Lock-free fast path for the hot lookup (per-batch/per-step call
        # sites hit this by name): metrics are never removed, and a plain
        # dict .get() of a fully-constructed value is safe under the GIL —
        # so callers don't need their own metric-object caches.
        existing = self._metrics.get(name)
        if existing is None:
            with self._lock:
                existing = self._metrics.get(name)
                if existing is None:
                    metric = factory()
                    self._metrics[name] = metric
                    return metric
        if existing.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as "
                f"{existing.kind}, not {kind}"
            )
        return existing

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, lambda: Counter(name), "counter")

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name), "gauge")

    def histogram(
        self, name: str, buckets: Sequence[float] = DEFAULT_MS_BUCKETS
    ) -> Histogram:
        hist = self._get_or_create(
            name, lambda: Histogram(name, buckets), "histogram"
        )
        # Hot-path callers (per-batch/per-step observe) pass the default:
        # skip rebuilding the float tuple for the common case — the
        # equality check still runs, so a custom-bucket re-registration
        # under the same name is caught either way.
        bounds = (DEFAULT_MS_BUCKETS if buckets is DEFAULT_MS_BUCKETS
                  else tuple(float(b) for b in buckets))
        if hist.bounds != bounds:
            # Same rationale as the kind check: silently returning the
            # first-registration buckets would leave a caller believing its
            # requested resolution took effect.
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{hist.bounds}, not {bounds}"
            )
        return hist

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> Dict[str, object]:
        """Name → metric, sorted — a stable snapshot for rendering."""
        with self._lock:
            return dict(sorted(self._metrics.items()))

    def snapshot(self) -> Dict[str, float]:
        """Flat scalar view: counters/gauges by name, histograms expanded to
        ``name_p50/p95/p99`` + ``name_count`` — the JSONL-friendly form."""
        out: Dict[str, float] = {}
        for name, metric in self.metrics().items():
            if isinstance(metric, Histogram):
                if metric.count:  # empty: percentiles are NaN, which
                    # json.dumps emits as a bare token strict parsers reject
                    for k, v in metric.percentiles().items():
                        out[f"{name}_{k}"] = v
                out[f"{name}_count"] = metric.count
            else:
                out[name] = metric.value  # type: ignore[union-attr]
        return out

    def render_prometheus(self) -> str:
        return render_prometheus(self)


def render_prometheus(registry: MetricsRegistry) -> str:
    """Prometheus text exposition format (version 0.0.4) for every metric
    in the registry — the payload ``obs/http.py`` serves at ``/metrics``."""
    lines: list = []
    for name, metric in registry.metrics().items():
        lines.append(f"# TYPE {name} {metric.kind}")
        if isinstance(metric, Histogram):
            counts, total_sum, total = metric.snapshot()
            cum = 0
            for bound, c in zip(metric.bounds, counts):
                cum += c
                lines.append(f'{name}_bucket{{le="{_fmt(bound)}"}} {cum}')
            cum += counts[-1]
            lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{name}_sum {_fmt(total_sum)}")
            lines.append(f"{name}_count {total}")
        else:
            lines.append(f"{name} {_fmt(metric.value)}")  # type: ignore
    return "\n".join(lines) + ("\n" if lines else "")


class RegistryDelta:
    """Windowed view over a registry: each :meth:`delta` returns what
    happened **since the previous call** — the form a controller (or a bench
    script that used to scrape ``/metrics`` twice and subtract by hand) can
    actually act on. Cumulative series answer "how much ever"; a control
    loop needs "how much in the last window".

    Output is one flat ``{name: float}`` dict per window:

    * counters → the window's increment (``name``),
    * gauges → the current value verbatim (``name`` — gauges are already
      instantaneous),
    * histograms → ``name_count`` / ``name_sum`` window increments plus
      ``name_p50/p95/p99`` interpolated over the *window's* bucket deltas
      (only when the window saw observations; a +Inf-bucket rank clamps to
      the top finite bound — delta windows have no per-window max).

    Metrics created after the first call simply appear with their full value
    as the first delta (their previous snapshot is implicitly zero). One
    tracker per consumer: two consumers sharing an instance would steal each
    other's windows.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else _DEFAULT
        # name -> last-seen raw state: float for counters, (counts, sum,
        # count) for histograms. Single-consumer by contract (no lock).
        self._prev: Dict[str, object] = {}

    def delta(self, qs: Iterable[float] = (50, 95, 99)) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for name, metric in self.registry.metrics().items():
            if isinstance(metric, Histogram):
                counts, total_sum, total = metric.snapshot()
                prev = self._prev.get(name)
                if prev is None:
                    prev = ([0] * len(counts), 0.0, 0)
                dcounts = [a - b for a, b in zip(counts, prev[0])]
                dcount = total - prev[2]
                out[f"{name}_count"] = float(dcount)
                out[f"{name}_sum"] = total_sum - prev[1]
                if dcount > 0:
                    for q in qs:
                        out[f"{name}_p{int(q)}"] = percentile_from_counts(
                            metric.bounds, dcounts, dcount, q
                        )
                self._prev[name] = (counts, total_sum, total)
            elif isinstance(metric, Counter):
                value = metric.value
                out[name] = value - float(self._prev.get(name, 0.0))
                self._prev[name] = value
            else:  # Gauge: instantaneous, passes through
                out[name] = metric.value  # type: ignore[union-attr]
        return out


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry where every instrumented layer meets —
    serve it once (``--metrics_port``) and the scrape sees the whole
    process."""
    return _DEFAULT
