"""Per-item cost ledger — what each plan item actually cost to decode.

The straggler problem (MinatoLoader, PAPERS.md 2509.10712) is per-ITEM:
one oversized JPEG on the re-encode path, one long token tail, and batch
assembly stalls at the slowest row. Metrics histograms say decode got
slow; only a ledger keyed the way the planner keys work can say *which
items* are slow — the seam a straggler-aware scheduler consumes.

A :class:`CostLedger` holds bounded per-item records keyed by the SAME
content hash :class:`~..data.cache.BatchCache` keys plan items with
(``item_fingerprint``), so a ledger row, a cache entry, and a plan item
all name the same work. Fields are whatever the decode path observed::

    {"key": "sha256:…", "n": 3, "decode_ms": 41.2, "decode_ms_max": 55.0,
     "entropy_ms": 12.1, "device_ms": 8.9, "bytes": 602112,
     "token_len": 512, "reencode": 1, "cache_hit": 0, "step": 17}

Recording is two-layered so deep decode internals need no plumbing:

* the decode *caller* (``DataService._produce``, the in-process decode
  seam) opens :func:`cost_context` around one item's decode and the
  ledger gets one merged record on exit;
* decode *internals* (``data/device_decode.py`` entropy loop,
  ``data/token_pack.py``) call :func:`note_cost` — a thread-local merge
  into whichever context is open, a no-op when none is (so workers,
  tests, and bare calls cost two attribute loads).

Worker-pool decode runs in worker processes: their ``note_cost`` calls
land in the worker's own (context-less) process and are dropped; the
server still records arrival-gap ``decode_ms`` + bytes per item, which
is the wait the planner schedules against. Memory is bounded (oldest
records fall off), registry summaries ride ``/metrics`` as ``cost_*``,
and ``LDT_COST_PATH`` appends one JSON line per record — the durable
form ``ldt costs report`` consumes.

Clock policy: durations arrive already measured (monotonic, LDT601);
the JSONL stamp is ``time.time_ns()`` — an epoch stamp meant to cross
process boundaries, per the lineage clock policy.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from .registry import MetricsRegistry, default_registry

__all__ = [
    "CostLedger",
    "default_ledger",
    "cost_context",
    "note_cost",
    "costs_main",
]

# Numeric fields where the historical MAX is the straggler signal (the
# slowest observation of an item, not its latest).
_TRACK_MAX = ("decode_ms",)
# Flag fields accumulated as counts (how often the slow path fired).
_FLAG_FIELDS = ("reencode", "cache_hit")
# Fields summarised into /metrics histograms on every record.
_HIST_FIELDS = ("decode_ms", "entropy_ms", "device_ms", "token_len")


class CostLedger:
    """Bounded, thread-safe per-item cost records (insertion-ordered
    ring: re-recording an item refreshes it to the young end)."""

    def __init__(self, capacity: int = 4096,
                 registry: Optional[MetricsRegistry] = None,
                 jsonl_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._capacity = max(1, capacity)
        self._records: "OrderedDict[str, dict]" = OrderedDict()
        self._registry = registry
        self._io_lock = threading.Lock()
        self._jsonl = None
        self._jsonl_path = jsonl_path or os.environ.get("LDT_COST_PATH")

    @property
    def registry(self) -> MetricsRegistry:
        if self._registry is None:
            self._registry = default_registry()
        return self._registry

    # -- recording ---------------------------------------------------------

    def record(self, key: Optional[str], **fields) -> None:
        """Merge one observation of item ``key`` (None — an unaddressable
        item — is dropped: a ledger row nobody can schedule is noise)."""
        if key is None:
            return
        clean = {}
        for name, value in fields.items():
            if isinstance(value, bool):
                clean[name] = int(value)
            elif isinstance(value, (int, float)):
                clean[name] = round(float(value), 3)
        with self._lock:
            rec = self._records.pop(key, None)
            if rec is None:
                rec = {"key": key, "n": 0}
            rec["n"] += 1
            for name, value in clean.items():
                if name in _FLAG_FIELDS:
                    rec[name] = rec.get(name, 0) + value
                else:
                    rec[name] = value
            for name in _TRACK_MAX:
                if name in clean:
                    prev = rec.get(f"{name}_max", clean[name])
                    rec[f"{name}_max"] = max(prev, clean[name])
            self._records[key] = rec
            while len(self._records) > self._capacity:
                self._records.popitem(last=False)
        reg = self.registry
        reg.counter("cost_records_total").inc()
        if clean.get("bytes"):
            reg.counter("cost_bytes_total").inc(clean["bytes"])
        if clean.get("reencode"):
            reg.counter("cost_reencode_total").inc(clean["reencode"])
        for name in _HIST_FIELDS:
            if name in clean:
                reg.histogram(f"cost_{name}").observe(clean[name])
        self._append_jsonl(key, clean)

    def _append_jsonl(self, key: str, fields: dict) -> None:
        if self._jsonl_path is None:
            return
        line = json.dumps(
            dict(fields, key=key, ns=time.time_ns())  # epoch stamp:
        ) + "\n"  # crosses processes into `ldt costs report` (LDT601)
        with self._io_lock:
            if self._jsonl_path is None:
                return
            if self._jsonl is None:
                try:
                    self._jsonl = open(self._jsonl_path, "a")
                except OSError:
                    self._jsonl_path = None  # never retry a bad path
                    return
            self._jsonl.write(line)
            self._jsonl.flush()

    # -- reading -----------------------------------------------------------

    def records(self) -> List[dict]:
        """Current records, oldest first (bounded by capacity)."""
        with self._lock:
            return [dict(r) for r in self._records.values()]

    def top(self, n: int = 3, by: str = "decode_ms_max") -> List[dict]:
        """The ``n`` costliest items — the straggler table's rows."""
        recs = self.records()
        recs.sort(key=lambda r: r.get(by, r.get("decode_ms", 0.0)),
                  reverse=True)
        return recs[:n]

    def close(self) -> None:
        with self._io_lock:
            self._jsonl_path = None
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None


_DEFAULT: Optional[CostLedger] = None
_DEFAULT_LOCK = threading.Lock()


def default_ledger() -> CostLedger:
    """The process-wide ledger (lazy, like the default tracer, so
    ``LDT_COST_PATH`` set by the entry point is honoured)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = CostLedger()
        return _DEFAULT


# -- thread-local context: decode internals report without plumbing --------

_TLS = threading.local()


class cost_context:
    """Context manager the decode CALLER opens around one item: every
    :func:`note_cost` on this thread merges into one record, written to
    ``ledger`` on exit (exceptions included — a decode that died half
    way is exactly the record a straggler hunt wants)."""

    def __init__(self, key: Optional[str],
                 ledger: Optional[CostLedger] = None, **fields):
        self._key = key
        self._ledger = ledger
        self._fields = dict(fields)
        self._prev = None

    def __enter__(self) -> "cost_context":
        self._prev = getattr(_TLS, "fields", None)
        _TLS.fields = self._fields
        return self

    def __exit__(self, *exc) -> None:
        _TLS.fields = self._prev
        ledger = self._ledger if self._ledger is not None else default_ledger()
        ledger.record(self._key, **self._fields)

    def note(self, **fields) -> None:
        self._fields.update(fields)


def note_cost(**fields) -> None:
    """Merge fields into the innermost open :func:`cost_context` on this
    thread; a no-op (two attribute loads) when none is open — decode
    internals call this unconditionally."""
    current = getattr(_TLS, "fields", None)
    if current is not None:
        current.update(fields)


# -- `ldt costs` CLI ---------------------------------------------------------


def costs_main(argv=None, out=None) -> int:
    """``ldt costs report`` — aggregate cost-ledger JSONL (written under
    ``LDT_COST_PATH``) into a straggler table. Returns exit status."""
    import argparse
    import sys

    out = out if out is not None else sys.stdout
    p = argparse.ArgumentParser(
        prog="ldt costs",
        description="Report per-item decode costs from cost-ledger JSONL",
    )
    sub = p.add_subparsers(dest="command")
    rep = sub.add_parser("report", help="aggregate cost JSONL → table")
    rep.add_argument(
        "--costs", action="append", default=None, metavar="JSONL",
        help="cost JSONL file(s) written under LDT_COST_PATH (repeatable; "
             "default: $LDT_COST_PATH or ldt-costs.jsonl)",
    )
    rep.add_argument("--top", type=int, default=10,
                     help="straggler rows to show (default 10)")
    args = p.parse_args(list(argv) if argv is not None else None)
    if args.command != "report":
        p.print_help(out)
        return 2
    paths = args.costs or [os.environ.get("LDT_COST_PATH", "ldt-costs.jsonl")]
    ledger = CostLedger(capacity=1 << 20, jsonl_path=None,
                        registry=MetricsRegistry())
    lines = 0
    parsed = []  # (key, fields) in file order, for the prediction replay
    for path in paths:
        if not os.path.exists(path):
            out.write(f"ldt costs: missing cost file {path}\n")
            continue
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, start=1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    out.write(
                        f"ldt costs: skipping undecodable line "
                        f"{path}:{lineno}\n"
                    )
                    continue
                if isinstance(rec, dict) and isinstance(rec.get("key"), str):
                    fields = {
                        k: v for k, v in rec.items() if k not in ("key", "ns")
                    }
                    ledger.record(rec["key"], **fields)
                    parsed.append((rec["key"], fields))
                    lines += 1
    # Predicted-vs-actual replay (data/schedule.py CostModel): walk the
    # ledger in recorded order, predicting each observation BEFORE folding
    # it in — exactly the error the straggler scheduler would have run
    # with. The per-key mean lands in the pred_err_ms column, so a
    # mispredicted straggler is diagnosable straight from this table.
    from ..data.schedule import CostModel

    model = CostModel()
    pred_err: dict = {}  # key -> [err_sum, n]
    for key, fields in parsed:
        ms = fields.get("decode_ms")
        if not isinstance(ms, (int, float)):
            continue
        err = abs(model.predict(key, fields) - float(ms))
        acc = pred_err.setdefault(key, [0.0, 0])
        acc[0] += err
        acc[1] += 1
        model.observe(key, float(ms), fields)
    recs = ledger.records()
    for rec in recs:
        acc = pred_err.get(rec["key"])
        if acc is not None:
            rec["pred_err_ms"] = round(acc[0] / acc[1], 3)
    if not recs:
        out.write(
            "ldt costs: no records — run with LDT_COST_PATH=<file> to "
            "record per-item costs\n"
        )
        return 2
    total_n = sum(r["n"] for r in recs)
    out.write(
        f"ldt costs: {len(recs)} items, {total_n} observations "
        f"({lines} lines)\n"
    )
    cols = ("n", "decode_ms_max", "decode_ms", "pred_err_ms", "entropy_ms",
            "device_ms", "bytes", "token_len", "reencode", "cache_hit")
    out.write("  " + " ".join(f"{c:>13}" for c in cols) + "  key\n")
    # Same straggler ordering as CostLedger.top(), over the annotated
    # records (top() re-copies and would drop the pred_err_ms join).
    recs.sort(key=lambda r: r.get("decode_ms_max", r.get("decode_ms", 0.0)),
              reverse=True)
    for rec in recs[:args.top]:
        row = " ".join(f"{rec.get(c, ''):>13}" for c in cols)
        out.write(f"  {row}  {rec['key'][:20]}\n")
    return 0
