"""Task registry — ``task_type`` string → (model, loss_fn, eval_fn).

API parity with ``get_model_and_loss``
(``/root/reference/modelling/get_model_and_loss.py:4-11``): the reference
registers only ``"classification"`` and raises ``ValueError`` otherwise; the
same contract is kept here (extended tasks live behind
:func:`~.tasks.get_task`, which this delegates to).
"""

from __future__ import annotations

from typing import Callable

from .tasks import get_task

__all__ = ["get_model_and_loss"]


def get_model_and_loss(
    task_type: str,
    num_classes: int,
    model_name: str = "resnet50",
    image_size: int = 224,
) -> tuple[object, Callable, Callable]:
    """Returns (flax model, loss_fn(logits, batch), eval_fn(logits, batch)).

    loss_fn → scalar mean cross-entropy; eval_fn → per-example top-1
    correctness (the ``evaluate`` contract,
    ``/root/reference/modelling/classification.py:20-32``).
    """
    if task_type != "classification":
        # Error-message parity: modelling/get_model_and_loss.py:10-11.
        raise ValueError(f"Invalid task type: {task_type}")
    task = get_task(
        "classification",
        num_classes=num_classes,
        model_name=model_name,
        image_size=image_size,
    )
    return task.model, task.loss, task.metric
