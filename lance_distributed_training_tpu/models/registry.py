"""Task registry — ``task_type`` string → (model, loss_fn, eval_fn).

Parity with ``get_model_and_loss``
(``/root/reference/modelling/get_model_and_loss.py:4-11``): only
``"classification"`` is registered; unknown task types raise ``ValueError``
with the reference's message shape. Extended with a ``model_name`` knob (the
reference hard-codes resnet50, ``modelling/classification.py:6``).

loss_fn(logits, batch) -> scalar; eval_fn(logits, batch) -> per-example
correctness (for top-1 accuracy, ``modelling/classification.py:20-32``).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import optax

from . import resnet as _resnet

__all__ = ["get_model_and_loss"]

_RESNETS = {
    "resnet18": _resnet.resnet18,
    "resnet34": _resnet.resnet34,
    "resnet50": _resnet.resnet50,
    "resnet101": _resnet.resnet101,
    "resnet152": _resnet.resnet152,
}


def _classification_loss(logits, batch) -> jnp.ndarray:
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["label"]
    ).mean()


def _classification_correct(logits, batch) -> jnp.ndarray:
    """Per-example top-1 correctness — summed/averaged by the caller
    (the ``evaluate`` equivalent, ``modelling/classification.py:20-32``)."""
    return (jnp.argmax(logits, axis=-1) == batch["label"]).astype(jnp.float32)


def get_model_and_loss(
    task_type: str,
    num_classes: int,
    model_name: str = "resnet50",
) -> tuple[object, Callable, Callable]:
    if task_type == "classification":
        try:
            ctor = _RESNETS[model_name]
        except KeyError:
            raise ValueError(
                f"Invalid model name: {model_name} (have {sorted(_RESNETS)})"
            ) from None
        model = ctor(num_classes=num_classes)
        return model, _classification_loss, _classification_correct
    raise ValueError(f"Invalid task type: {task_type}")
