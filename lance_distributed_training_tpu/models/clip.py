"""CLIP-style dual-tower contrastive model — the mixed-modal arm.

Covers the BASELINE LAION config ("image+caption → CLIP contrastive
(mixed-modal TPU collate)"; BASELINE.json configs[4]). Absent from the
reference (vision-only, SURVEY.md §5); built the TPU way:

* image tower: the NHWC Flax ResNet (:mod:`.resnet`) with its head acting as
  the projection,
* text tower: the pre-LN transformer encoder (:mod:`.transformer`,
  ``head='none'``) with masked mean-pooling + a projection,
* **global-batch contrastive loss for free**: the step is jitted with the
  batch sharded ``P('data')``; the ``img @ txt.T`` similarity matrix spans
  the full global batch, so XLA inserts the cross-device all-gather that
  torch implementations hand-write with ``all_gather`` + ``stop_grad``
  tricks. No per-rank negatives-only approximation.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from .resnet import BasicBlock, BottleneckBlock, ResNet
from .transformer import TransformerEncoder

__all__ = ["CLIP", "clip_resnet50_bert", "clip_tiny", "clip_contrastive_loss"]


def _masked_mean(x, mask):
    mask = mask.astype(x.dtype)[..., None]
    total = (x * mask).sum(axis=1)
    count = jnp.maximum(mask.sum(axis=1), 1.0)
    return total / count


class CLIP(nn.Module):
    """Dual-tower model: ``__call__(batch)`` → (img_emb, txt_emb, logit_scale).

    Batch keys: ``image`` (normalized NHWC), ``input_ids``,
    ``attention_mask`` — the mixed-modal collate produced by
    :class:`..data.decode.ImageTextDecoder`.
    """

    embed_dim: int = 512
    image_stage_sizes: tuple = (3, 4, 6, 3)
    image_block: Any = BottleneckBlock
    vocab_size: int = 30522
    text_hidden: int = 512
    text_layers: int = 6
    text_heads: int = 8
    text_mlp_dim: int = 2048
    max_len: int = 77
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, images, input_ids, attention_mask, train: bool = True):
        img_emb = ResNet(
            stage_sizes=self.image_stage_sizes,
            block_cls=self.image_block,
            num_classes=self.embed_dim,  # classification head = projection
            dtype=self.dtype,
            name="image_tower",
        )(images, train=train)

        hidden = TransformerEncoder(
            vocab_size=self.vocab_size,
            hidden_size=self.text_hidden,
            num_layers=self.text_layers,
            num_heads=self.text_heads,
            mlp_dim=self.text_mlp_dim,
            max_len=self.max_len,
            dtype=self.dtype,
            head="none",
            name="text_tower",
        )(input_ids, attention_mask, train=train)
        txt_emb = nn.Dense(self.embed_dim, dtype=jnp.float32,
                           param_dtype=jnp.float32, name="text_proj")(
            _masked_mean(hidden.astype(jnp.float32), attention_mask)
        )

        img_emb = img_emb / jnp.maximum(
            jnp.linalg.norm(img_emb, axis=-1, keepdims=True), 1e-6
        )
        txt_emb = txt_emb / jnp.maximum(
            jnp.linalg.norm(txt_emb, axis=-1, keepdims=True), 1e-6
        )
        logit_scale = self.param(
            "logit_scale", nn.initializers.constant(jnp.log(1 / 0.07)), ()
        )
        return img_emb, txt_emb, jnp.exp(logit_scale)


def clip_contrastive_loss(img_emb, txt_emb, logit_scale):
    """Symmetric InfoNCE over the GLOBAL batch.

    Under ``P('data')`` input sharding the [B, B] similarity einsum forces the
    all-gather; both softmax directions use the full negative set.
    """
    logits = logit_scale * img_emb @ txt_emb.T  # [B, B]
    labels = jnp.arange(logits.shape[0])
    li = -jnp.take_along_axis(
        nn.log_softmax(logits, axis=1), labels[:, None], axis=1
    ).mean()
    lt = -jnp.take_along_axis(
        nn.log_softmax(logits, axis=0), labels[None, :], axis=0
    ).mean()
    return 0.5 * (li + lt)


clip_resnet50_bert = partial(
    CLIP, embed_dim=512, image_stage_sizes=(3, 4, 6, 3),
    image_block=BottleneckBlock, text_hidden=512, text_layers=6,
)
clip_tiny = partial(
    CLIP, embed_dim=64, image_stage_sizes=(1, 1, 1, 1), image_block=BasicBlock,
    vocab_size=1000, text_hidden=64, text_layers=2, text_heads=2,
    text_mlp_dim=128, max_len=16,
)
