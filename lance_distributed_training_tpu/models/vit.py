"""Vision Transformer — the second classification family in the zoo.

The reference's zoo is a single torchvision ResNet-50
(``/root/reference/modelling/classification.py:6-10``); ViT is the natural
TPU-first addition: the whole forward is patch-embedding + encoder matmuls
(pure MXU work, no conv-specific layout concerns), and it reuses
:class:`.transformer.EncoderBlock` — so tensor-parallel partition rules,
remat, and the alternative attention backends apply to it unchanged.

Classification head: mean-pooled tokens → LayerNorm → Dense (the simple
pooling variant; no CLS token so sequence length stays a clean patch grid).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from .transformer import EncoderBlock

__all__ = ["ViT", "vit_tiny", "vit_small", "vit_base"]


class ViT(nn.Module):
    """``__call__(images_f32_nhwc, train) -> logits [B, num_classes]``."""

    num_classes: int
    patch_size: int = 16
    hidden_size: int = 384
    num_layers: int = 12
    num_heads: int = 6
    mlp_dim: int = 1536
    dtype: Any = jnp.bfloat16
    remat: bool = False
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        b, h, w, c = x.shape
        if h % self.patch_size or w % self.patch_size:
            raise ValueError(
                f"image {h}x{w} not divisible by patch {self.patch_size}"
            )
        # Patchify = one strided conv straight onto the MXU.
        x = nn.Conv(
            self.hidden_size,
            (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="patch_embed",
        )(x.astype(self.dtype))
        seq = (h // self.patch_size) * (w // self.patch_size)
        x = x.reshape(b, seq, self.hidden_size)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (seq, self.hidden_size), jnp.float32,
        )
        x = x + pos.astype(self.dtype)

        block = EncoderBlock
        if self.remat:
            block = nn.remat(EncoderBlock, static_argnums=())
        for i in range(self.num_layers):
            x = block(self.num_heads, self.mlp_dim, self.dtype,
                      attention_fn=self.attention_fn, name=f"layer_{i}")(x)
        x = x.mean(axis=1)  # token mean-pool
        x = nn.LayerNorm(dtype=jnp.float32, param_dtype=jnp.float32,
                         name="ln_final")(x.astype(jnp.float32))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(x)


vit_tiny = partial(ViT, hidden_size=64, num_layers=2, num_heads=2,
                   mlp_dim=128, patch_size=8)
vit_small = partial(ViT, hidden_size=384, num_layers=12, num_heads=6,
                    mlp_dim=1536)
vit_base = partial(ViT, hidden_size=768, num_layers=12, num_heads=12,
                   mlp_dim=3072)
