"""Task abstraction: model + forward + loss + metric as one unit.

Generalises the reference's ``get_model_and_loss(task_type, num_classes) →
(model, loss_fn, eval_fn)`` contract
(``/root/reference/modelling/get_model_and_loss.py:4-11``) so ONE jitted
train step serves every task family. Each task owns:

* ``init_variables`` — parameter/state init,
* ``forward(variables, batch, train, rng)`` — including device-side input
  prep (normalize/augment for images, on-device MLM masking for text: all
  work that the reference did per-row on host is fused into the step here),
* ``loss(outputs, batch)`` and ``metric(outputs, batch)``.

Registered: ``classification`` (reference parity), ``masked_lm`` (BASELINE
C4/BERT config), ``contrastive`` (BASELINE LAION/CLIP config).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from ..ops.image import normalize_images, random_flip
from . import resnet as _resnet
from .clip import CLIP, clip_contrastive_loss, clip_resnet50_bert, clip_tiny
from .transformer import bert_base, bert_small, gpt_base, gpt_small

__all__ = ["Task", "get_task", "TASK_REGISTRY"]


@dataclasses.dataclass(frozen=True)
class Task:
    name: str
    model: Any
    init_variables: Callable  # (rng) -> variables
    forward: Callable  # (variables, batch, train, rng) -> (outputs, new_state|None)
    loss: Callable  # (outputs, batch) -> scalar
    metric: Callable  # (outputs, batch) -> per-example float array
    metric_name: str = "accuracy"


# ---------------------------------------------------------------- classification
_RESNETS = {
    "resnet18": _resnet.resnet18,
    "resnet34": _resnet.resnet34,
    "resnet50": _resnet.resnet50,
    "resnet101": _resnet.resnet101,
    "resnet152": _resnet.resnet152,
}


def _classifiers() -> dict:
    from . import vit as _vit

    return {
        **_RESNETS,
        "vit_tiny": _vit.vit_tiny,
        "vit_small": _vit.vit_small,
        "vit_base": _vit.vit_base,
    }


def _classification_task(num_classes: int, model_name: str, image_size: int,
                         augment: bool, param_dtype=None) -> Task:
    registry = _classifiers()
    try:
        ctor = registry[model_name]
    except KeyError:
        raise ValueError(
            f"Invalid model name: {model_name} (have {sorted(registry)})"
        ) from None
    kwargs = {"num_classes": num_classes}
    if param_dtype is not None:
        if model_name not in _RESNETS:
            raise ValueError(
                f"param_dtype override supports the ResNet family; got "
                f"{model_name!r}"
            )
        kwargs["param_dtype"] = param_dtype
    model = ctor(**kwargs)

    def init_variables(rng):
        return model.init(
            rng, jnp.zeros((1, image_size, image_size, 3), jnp.float32),
            train=False,
        )

    def forward(variables, batch, train, rng):
        images = normalize_images(batch["image"])
        if train and augment and rng is not None:
            images = random_flip(rng, images)
        if train:
            logits, new_state = model.apply(
                variables, images, train=True, mutable=["batch_stats"]
            )
            return logits, new_state
        logits = model.apply(variables, images, train=False)
        return logits, None

    def loss(logits, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()

    def metric(logits, batch):
        return (jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32)

    return Task("classification", model, init_variables, forward, loss, metric)


# ---------------------------------------------------------------- masked LM
def _masked_lm_task(vocab_size: Optional[int], model_name: str, seq_len: int,
                    mask_prob: float = 0.15, mask_id: int = 1,
                    attention_fn: Optional[Callable] = None,
                    remat: bool = False, num_experts: int = 0,
                    moe_every: int = 2,
                    aux_loss_weight: float = 0.01) -> Task:
    ctor = {"bert_base": bert_base, "bert_small": bert_small}.get(model_name)
    if ctor is None:
        raise ValueError(f"Invalid model name: {model_name} "
                         "(have ['bert_base', 'bert_small'])")
    model = ctor(vocab_size=vocab_size or 30522, max_len=seq_len,
                 attention_fn=attention_fn, remat=remat,
                 num_experts=num_experts, moe_every=moe_every)

    def init_variables(rng):
        ids = jnp.zeros((1, seq_len), jnp.int32)
        return model.init(rng, ids, jnp.ones((1, seq_len), jnp.int8),
                          train=False)

    def forward(variables, batch, train, rng):
        ids = batch["input_ids"].astype(jnp.int32)
        mask = batch["attention_mask"]
        # Packed batches (the ragged token plane, ops/token_device.py):
        # segment ids gate attention at sequence boundaries and position
        # ids restart the positional embedding per packed sequence. Absent
        # (the padded arm) the model runs its historical row-wise path.
        seg = batch.get("segment_ids")
        pos = batch.get("position_ids")
        if train and rng is not None:
            # On-device BERT masking: static shapes, no host RNG. The masked
            # positions double as the loss targets.
            mlm_mask = (
                jax.random.bernoulli(rng, mask_prob, ids.shape)
                & (mask > 0)
            )
        else:
            # Eval: deterministic mask (every ~1/mask_prob-th position) so
            # masked-token accuracy measures real infilling, not copying.
            stride = max(int(round(1.0 / mask_prob)), 1)
            positions = jnp.arange(ids.shape[1])
            mlm_mask = ((positions % stride) == 0)[None, :] & (mask > 0)
        corrupted = jnp.where(mlm_mask, mask_id, ids)
        aux = jnp.zeros((), jnp.float32)
        if train and num_experts > 0:
            # MoE blocks sow their switch load-balance terms; collect them.
            logits, sown = model.apply(
                variables, corrupted, mask, train=True, mutable=["aux_loss"],
                segment_ids=seg, position_ids=pos,
            )
            for leaf in jax.tree_util.tree_leaves(sown.get("aux_loss", {})):
                aux = aux + leaf
        else:
            logits = model.apply(variables, corrupted, mask, train=train,
                                 segment_ids=seg, position_ids=pos)
        return (logits, mlm_mask, aux), None

    def loss(outputs, batch):
        logits, mlm_mask, aux = outputs
        targets = batch["input_ids"].astype(jnp.int32)
        raw = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        w = mlm_mask.astype(jnp.float32)
        return (raw * w).sum() / jnp.maximum(w.sum(), 1.0) + (
            aux_loss_weight * aux
        )

    def metric(outputs, batch):
        logits, mlm_mask, _aux = outputs
        targets = batch["input_ids"].astype(jnp.int32)
        hit = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
        w = mlm_mask.astype(jnp.float32)
        # Per-example masked-token accuracy.
        return (hit * w).sum(-1) / jnp.maximum(w.sum(-1), 1.0)

    return Task("masked_lm", model, init_variables, forward, loss, metric,
                metric_name="masked_token_accuracy")


# ---------------------------------------------------------------- causal LM
def _causal_lm_task(vocab_size: Optional[int], model_name: str, seq_len: int,
                    attention_fn: Optional[Callable] = None,
                    remat: bool = False, num_experts: int = 0,
                    moe_every: int = 2,
                    aux_loss_weight: float = 0.01) -> Task:
    """Decoder-only next-token prediction (GPT family) over the same packed
    token columns as masked-LM (``create_text_token_dataset``) — the text arm
    beyond the reference's vision-only scope, sharing the trainer, samplers
    and storage unchanged."""
    ctor = {"gpt_base": gpt_base, "gpt_small": gpt_small}.get(model_name)
    if ctor is None:
        raise ValueError(f"Invalid model name: {model_name} "
                         "(have ['gpt_base', 'gpt_small'])")
    model = ctor(vocab_size=vocab_size or 50257, max_len=seq_len,
                 attention_fn=attention_fn, remat=remat,
                 num_experts=num_experts, moe_every=moe_every)

    def init_variables(rng):
        ids = jnp.zeros((1, seq_len), jnp.int32)
        return model.init(rng, ids, jnp.ones((1, seq_len), jnp.int8),
                          train=False)

    def forward(variables, batch, train, rng):
        ids = batch["input_ids"].astype(jnp.int32)
        mask = batch["attention_mask"]
        # Packed batches: segments gate the (already causal) attention at
        # sequence boundaries; positions restart per packed sequence.
        seg = batch.get("segment_ids")
        pos = batch.get("position_ids")
        aux = jnp.zeros((), jnp.float32)
        if train and num_experts > 0:
            logits, sown = model.apply(
                variables, ids, mask, train=True, mutable=["aux_loss"],
                segment_ids=seg, position_ids=pos,
            )
            for leaf in jax.tree_util.tree_leaves(sown.get("aux_loss", {})):
                aux = aux + leaf
        else:
            logits = model.apply(variables, ids, mask, train=train,
                                 segment_ids=seg, position_ids=pos)
        return (logits, aux), None

    def _shifted(outputs, batch):
        logits, aux = outputs
        ids = batch["input_ids"].astype(jnp.int32)
        # Predict token t+1 from positions <= t; weight by the target's
        # validity so padding after a final partial pack contributes nothing.
        targets = ids[:, 1:]
        w = batch["attention_mask"][:, 1:].astype(jnp.float32)
        seg = batch.get("segment_ids")
        if seg is not None:
            # Packed rows: a position whose target belongs to a DIFFERENT
            # packed sequence is a junction, not a prediction — weight it
            # out, so the packed loss matches per-sequence semantics.
            w = w * (seg[:, 1:] == seg[:, :-1]).astype(jnp.float32)
        return logits[:, :-1], targets, w, aux

    def loss(outputs, batch):
        logits, targets, w, aux = _shifted(outputs, batch)
        raw = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        return (raw * w).sum() / jnp.maximum(w.sum(), 1.0) + (
            aux_loss_weight * aux
        )

    def metric(outputs, batch):
        logits, targets, w, _aux = _shifted(outputs, batch)
        hit = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
        return (hit * w).sum(-1) / jnp.maximum(w.sum(-1), 1.0)

    return Task("causal_lm", model, init_variables, forward, loss, metric,
                metric_name="next_token_accuracy")


# ------------------------------------------------------- pipelined masked LM
_BERT_DIMS = {
    # (hidden, layers, heads, mlp_dim) — mirrors bert_base / bert_small.
    "bert_base": (768, 12, 12, 3072),
    "bert_small": (256, 4, 4, 1024),
}


def _pipelined_masked_lm_task(
    vocab_size: Optional[int],
    model_name: str,
    seq_len: int,
    mesh,
    n_microbatches: int,
    mask_prob: float = 0.15,
    mask_id: int = 1,
    dtype=jnp.bfloat16,
) -> Task:
    """Masked-LM with the encoder stack run through the GPipe pipeline
    (:mod:`..parallel.pipeline_parallel`) over the mesh's ``'pipe'`` axis.

    The L encoder blocks' params are stacked ``[L, ...]`` and sharded
    ``P('pipe')`` (each stage holds ``L/pp`` layers and scans them);
    embedding/head stay replicated outside the pipeline. Designed for PACKED
    sequences (the C4 config,
    :func:`..data.authoring.create_text_token_dataset` with ``pack=True``):
    attention runs unmasked inside the pipeline, so padded rows should be
    rare (only a dataset's final partial pack); the MLM loss still respects
    ``attention_mask``.
    """
    from ..parallel.pipeline_parallel import pipeline_apply, stack_stage_params
    from .transformer import EncoderBlock

    if model_name not in _BERT_DIMS:
        raise ValueError(f"Invalid model name: {model_name} "
                         f"(have {sorted(_BERT_DIMS)})")
    hidden, layers, heads, mlp_dim = _BERT_DIMS[model_name]
    vocab = vocab_size or 30522
    pp = mesh.shape.get("pipe", 1)
    if layers % pp:
        raise ValueError(f"{layers} layers not divisible by pipe={pp}")
    block = EncoderBlock(num_heads=heads, mlp_dim=mlp_dim, dtype=dtype)

    def init_variables(rng):
        rngs = jax.random.split(rng, layers + 2)
        dummy = jnp.zeros((1, seq_len, hidden), dtype)
        blocks = stack_stage_params(
            [block.init(rngs[i], dummy)["params"] for i in range(layers)]
        )
        init = jax.nn.initializers.normal(0.02)
        return {
            "params": {
                "blocks": blocks,
                "tok_embed": init(rngs[-2], (vocab, hidden), jnp.float32),
                "pos_embed": init(rngs[-1], (seq_len, hidden), jnp.float32),
                "ln_scale": jnp.ones((hidden,), jnp.float32),
                "ln_bias": jnp.zeros((hidden,), jnp.float32),
            }
        }

    def stage_fn(stage_params, h):
        return jax.lax.scan(
            lambda carry, q: (block.apply({"params": q}, carry, None), None),
            h,
            stage_params,
        )[0]

    def forward(variables, batch, train, rng):
        p = variables["params"]
        ids = batch["input_ids"].astype(jnp.int32)
        valid = batch["attention_mask"] > 0
        if train and rng is not None:
            mlm_mask = jax.random.bernoulli(rng, mask_prob, ids.shape) & valid
        else:
            stride = max(int(round(1.0 / mask_prob)), 1)
            positions = jnp.arange(ids.shape[1])
            mlm_mask = ((positions % stride) == 0)[None, :] & valid
        corrupted = jnp.where(mlm_mask, mask_id, ids)
        x = p["tok_embed"][corrupted].astype(dtype)
        x = x + p["pos_embed"][None, : ids.shape[1]].astype(dtype)
        x = pipeline_apply(stage_fn, p["blocks"], x, mesh, n_microbatches)
        x32 = x.astype(jnp.float32)
        mean = x32.mean(-1, keepdims=True)
        var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
        x32 = (x32 - mean) / jnp.sqrt(var + 1e-6) * p["ln_scale"] + p["ln_bias"]
        logits = x32 @ p["tok_embed"].T  # tied head
        return (logits, mlm_mask, jnp.zeros((), jnp.float32)), None

    def loss(outputs, batch):
        logits, mlm_mask, _aux = outputs
        targets = batch["input_ids"].astype(jnp.int32)
        raw = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        w = mlm_mask.astype(jnp.float32)
        return (raw * w).sum() / jnp.maximum(w.sum(), 1.0)

    def metric(outputs, batch):
        logits, mlm_mask, _aux = outputs
        targets = batch["input_ids"].astype(jnp.int32)
        hit = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
        w = mlm_mask.astype(jnp.float32)
        return (hit * w).sum(-1) / jnp.maximum(w.sum(-1), 1.0)

    return Task("masked_lm_pp", block, init_variables, forward, loss, metric,
                metric_name="masked_token_accuracy")


# ---------------------------------------------------------------- contrastive
def _contrastive_task(model_name: str, image_size: int, seq_len: int,
                      vocab_size: Optional[int], augment: bool = True) -> Task:
    ctor = {"clip_resnet50_bert": clip_resnet50_bert, "clip_tiny": clip_tiny}.get(
        model_name
    )
    if ctor is None:
        raise ValueError(f"Invalid model name: {model_name} "
                         "(have ['clip_resnet50_bert', 'clip_tiny'])")
    # vocab_size=None → the preset's own default (clip_tiny: 1000,
    # clip_resnet50_bert: 30522); an explicit value always wins.
    kwargs = {"max_len": seq_len}
    if vocab_size is not None:
        kwargs["vocab_size"] = vocab_size
    model: CLIP = ctor(**kwargs)

    def init_variables(rng):
        return model.init(
            rng,
            jnp.zeros((2, image_size, image_size, 3), jnp.float32),
            jnp.zeros((2, seq_len), jnp.int32),
            jnp.ones((2, seq_len), jnp.int8),
            train=False,
        )

    def forward(variables, batch, train, rng):
        images = normalize_images(batch["image"])
        if train and augment and rng is not None:
            images = random_flip(rng, images)
        if train:
            out, new_state = model.apply(
                variables, images, batch["input_ids"].astype(jnp.int32),
                batch["attention_mask"], train=True, mutable=["batch_stats"],
            )
            return out, new_state
        out = model.apply(
            variables, images, batch["input_ids"].astype(jnp.int32),
            batch["attention_mask"], train=False,
        )
        return out, None

    def loss(outputs, batch):
        img_emb, txt_emb, scale = outputs
        return clip_contrastive_loss(img_emb, txt_emb, scale)

    def metric(outputs, batch):
        img_emb, txt_emb, scale = outputs
        logits = img_emb @ txt_emb.T
        return (jnp.argmax(logits, -1) == jnp.arange(logits.shape[0])).astype(
            jnp.float32
        )

    return Task("contrastive", model, init_variables, forward, loss, metric,
                metric_name="retrieval_top1")


def get_task(
    task_type: str,
    *,
    num_classes: int = 101,
    model_name: Optional[str] = None,
    image_size: int = 224,
    seq_len: int = 128,
    vocab_size: Optional[int] = None,
    augment: bool = True,
    attention_fn: Optional[Callable] = None,
    remat: bool = False,
    num_experts: int = 0,
    moe_every: int = 2,
    pipeline_parallelism: int = 1,
    pp_microbatches: int = 4,
    mesh=None,
    param_dtype=None,
) -> Task:
    """``vocab_size=None`` means "the model's own default" (bert_*: 30522,
    clip_tiny: 1000, clip_resnet50_bert: 30522); explicit values always
    apply verbatim. ``param_dtype`` overrides the parameter/optimizer-state
    dtype (ResNet family only; e.g. ``jnp.bfloat16`` halves weight HBM)."""
    if task_type == "classification":
        return _classification_task(
            num_classes, model_name or "resnet50", image_size, augment,
            param_dtype=param_dtype,
        )
    if task_type == "masked_lm":
        if pipeline_parallelism > 1:
            if attention_fn is not None or num_experts:
                raise ValueError(
                    "pipeline_parallelism composes with dp only "
                    "(not seq/flash/moe) in this release"
                )
            return _pipelined_masked_lm_task(
                vocab_size, model_name or "bert_base", seq_len, mesh,
                pp_microbatches,
            )
        return _masked_lm_task(vocab_size, model_name or "bert_base", seq_len,
                               attention_fn=attention_fn, remat=remat,
                               num_experts=num_experts, moe_every=moe_every)
    if task_type == "causal_lm":
        if pipeline_parallelism > 1:
            raise ValueError(
                "pipeline_parallelism supports masked_lm only in this release"
            )
        return _causal_lm_task(vocab_size, model_name or "gpt_base", seq_len,
                               attention_fn=attention_fn, remat=remat,
                               num_experts=num_experts, moe_every=moe_every)
    if task_type == "contrastive":
        return _contrastive_task(
            model_name or "clip_resnet50_bert", image_size, seq_len,
            vocab_size, augment=augment,
        )
    # Error-message parity: modelling/get_model_and_loss.py:10-11.
    raise ValueError(f"Invalid task type: {task_type}")


TASK_REGISTRY = ("classification", "masked_lm", "causal_lm", "contrastive")
