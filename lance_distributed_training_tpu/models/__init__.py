"""Model zoo + task registry — Flax replacement for ``modelling/``."""

from .registry import get_model_and_loss  # noqa: F401
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152  # noqa: F401
from .tasks import Task, get_task, TASK_REGISTRY  # noqa: F401
from .transformer import (  # noqa: F401
    TransformerEncoder,
    bert_base,
    bert_small,
    gpt_base,
    gpt_small,
)
from .clip import CLIP, clip_resnet50_bert, clip_tiny  # noqa: F401
from .vit import ViT, vit_base, vit_small, vit_tiny  # noqa: F401
