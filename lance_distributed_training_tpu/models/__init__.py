"""Model zoo + task registry — Flax replacement for ``modelling/``."""

from .registry import get_model_and_loss  # noqa: F401
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152  # noqa: F401
