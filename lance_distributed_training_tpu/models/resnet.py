"""Flax ResNet family — TPU-native replacement for torchvision ResNet-50.

The reference's model layer (``/root/reference/modelling/classification.py``)
wraps a pretrained torchvision ``resnet50``, re-initialises ``conv1`` (:8 —
destroying pretrained weights, an acknowledged quirk we do NOT replicate) and
swaps ``fc`` for a ``num_classes`` head (:9).

TPU-first choices:
* NHWC layout throughout (TPU conv layout; torchvision is NCHW),
* bfloat16 compute / float32 params & batch-norm statistics — keeps the MXU
  fed at its native precision without destabilising BN,
* no data-dependent Python control flow: the whole forward is one traced
  graph, `lax`-free because the topology is static.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152"]

ModuleDef = Any


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        # Explicit symmetric padding: XLA's SAME pads (0,1) under stride 2,
        # torchvision pads (1,1) — symmetric keeps imported pretrained
        # weights numerically exact (models/pretrained.py).
        y = self.conv(self.filters, (3, 3), self.strides,
                      padding=[(1, 1), (1, 1)])(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(
                residual
            )
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        # Symmetric padding for torchvision parity (see BasicBlock).
        y = self.conv(self.filters, (3, 3), self.strides,
                      padding=[(1, 1), (1, 1)])(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale: residual branch starts as identity,
        # the standard trick for stable large-batch training.
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """NHWC ResNet; returns logits ``[B, num_classes]``."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    # float32 params are the stable default; bfloat16 halves param +
    # optimizer-state HBM and the per-step weight traffic (a deliberate
    # perf/stability trade the bench sweep measures explicitly).
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        act = nn.relu

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="norm_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    conv=conv,
                    norm=norm,
                    act=act,
                    strides=strides,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Head in float32 for a numerically stable softmax.
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="head")(x)
        return x


resnet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
resnet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
resnet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
resnet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
resnet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)
