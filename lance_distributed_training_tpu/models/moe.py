"""Mixture-of-Experts MLP — expert parallelism for the transformer arm.

Beyond the reference's scope (DP-only, SURVEY.md §2.3), built the TPU way:
top-1 switch routing expressed entirely as einsums over a dense dispatch
tensor — no scatter/gather, no data-dependent shapes, so XLA tiles everything
onto the MXU and the SPMD partitioner shards the expert dimension over the
mesh's ``'model'`` axis (see ``MOE_RULES`` in :mod:`..parallel.sharding`):
each device group holds ``num_experts / tp`` experts and the dispatch einsum
becomes the expert all-to-all.

Routing follows the Switch Transformer recipe: top-1 expert per token, fixed
per-expert capacity ``ceil(capacity_factor * tokens / num_experts)`` (static
shape!), overflow tokens pass through the residual unchanged, and a
load-balance auxiliary loss (fraction-routed × mean-probability per expert)
is exposed via ``sow`` for the task loss to pick up.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["MoEMLP"]


class MoEMLP(nn.Module):
    """Switch-routed expert MLP: ``[B, S, H] -> [B, S, H]``.

    Capacity note: tokens beyond an expert's queue contribute zero to the
    output (their dispatch weight is masked), which with the transformer's
    residual connection means they simply skip the MLP — the standard
    overflow behavior.
    """

    num_experts: int
    mlp_dim: int
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, s, h = x.shape
        t = b * s
        e = self.num_experts
        capacity = max(1, int(self.capacity_factor * t / e))
        tokens = x.reshape(t, h)

        # Router in f32 for a stable softmax.
        logits = nn.Dense(e, dtype=jnp.float32, param_dtype=jnp.float32,
                          name="router")(tokens.astype(jnp.float32))
        probs = nn.softmax(logits, axis=-1)  # [T, E]
        expert_index = jnp.argmax(probs, axis=-1)  # [T]
        expert_prob = jnp.max(probs, axis=-1)  # gate value of the winner

        onehot = jax.nn.one_hot(expert_index, e, dtype=jnp.float32)  # [T, E]
        # Position of each token in its expert's queue (1-based), then mask
        # out tokens past capacity — all static shapes.
        position = jnp.cumsum(onehot, axis=0) * onehot  # [T, E]
        within = (position > 0) & (position <= capacity)
        pos_onehot = jax.nn.one_hot(
            (position - 1.0).astype(jnp.int32), capacity, dtype=jnp.float32
        )  # [T, E, C]
        dispatch = pos_onehot * within[..., None].astype(jnp.float32)
        combine = dispatch * expert_prob[:, None, None]

        # Expert queues: [E, C, H] — the einsum the partitioner turns into
        # the expert all-to-all when E is sharded.
        expert_in = jnp.einsum(
            "tec,th->ech", dispatch.astype(self.dtype), tokens.astype(self.dtype)
        )
        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(), (e, h, self.mlp_dim),
            jnp.float32,
        )
        b_in = self.param("b_in", nn.initializers.zeros_init(),
                          (e, self.mlp_dim), jnp.float32)
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(), (e, self.mlp_dim, h),
            jnp.float32,
        )
        b_out = self.param("b_out", nn.initializers.zeros_init(), (e, h),
                           jnp.float32)
        hidden = nn.gelu(
            jnp.einsum("ech,ehm->ecm", expert_in, w_in.astype(self.dtype))
            + b_in[:, None, :].astype(self.dtype)
        )
        expert_out = (
            jnp.einsum("ecm,emh->ech", hidden, w_out.astype(self.dtype))
            + b_out[:, None, :].astype(self.dtype)
        )
        y = jnp.einsum(
            "tec,ech->th", combine.astype(self.dtype), expert_out
        ).reshape(b, s, h)

        # Switch load-balance loss: E * Σ_e (fraction routed to e) ×
        # (mean router prob of e); minimised by uniform routing.
        frac = onehot.mean(axis=0)
        mean_prob = probs.mean(axis=0)
        self.sow("aux_loss", "load_balance",
                 e * jnp.sum(frac * mean_prob))
        return y
