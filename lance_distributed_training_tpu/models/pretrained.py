"""torchvision → Flax pretrained-weight import (transfer learning).

The reference fine-tunes a *pretrained* torchvision ResNet-50 — its model
layer is ``models.resnet50(weights=ResNet50_Weights.DEFAULT)`` with a fresh
``fc`` head (``/root/reference/modelling/classification.py:6-10``). This
module reproduces that task shape for the Flax zoo: a torch ``state_dict``
(torchvision key naming) converts into :class:`~.resnet.ResNet` variables —
NCHW→HWIO kernel transposes, BN scale/bias/running stats — and the
classifier head stays freshly initialised whenever its shape differs from
the checkpoint's (the reference always swaps the head; matching shapes are
imported so a 1000-class run round-trips exactly).

torch is a host-side dependency only (CPU wheel in this image): it reads the
checkpoint; everything after ``.numpy()`` is numpy/JAX. No torchvision
needed — the key schema is data, not code.
"""

from __future__ import annotations

import os
from typing import Any, Mapping

import jax
import numpy as np

__all__ = ["load_torch_state_dict", "torchvision_resnet_to_flax"]

# torchvision block names → (stage_sizes, flax block class name), mirroring
# models/resnet.py's constructors.
_STAGES = {
    "resnet18": ((2, 2, 2, 2), "BasicBlock"),
    "resnet34": ((3, 4, 6, 3), "BasicBlock"),
    "resnet50": ((3, 4, 6, 3), "BottleneckBlock"),
    "resnet101": ((3, 4, 23, 3), "BottleneckBlock"),
    "resnet152": ((3, 8, 36, 3), "BottleneckBlock"),
}


def load_torch_state_dict(path: str) -> dict[str, np.ndarray]:
    """Read a ``torch.save``'d checkpoint into ``{key: float32 ndarray}``.

    Accepts both a bare ``state_dict`` and the common ``{"state_dict": ...}``
    /  ``{"model": ...}`` wrappers; strips ``module.`` (DDP) prefixes the way
    torch users expect.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(f"pretrained checkpoint not found: {path}")
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    for wrapper in ("state_dict", "model"):
        if isinstance(obj, dict) and wrapper in obj and isinstance(
            obj[wrapper], dict
        ):
            obj = obj[wrapper]
    out = {}
    for k, v in obj.items():
        if k.startswith("module."):
            k = k[len("module."):]
        if hasattr(v, "numpy"):
            out[k] = np.asarray(v.detach().to(torch.float32).numpy())
    return out


def _t_conv(w: np.ndarray) -> np.ndarray:
    """torch OIHW conv weight → Flax HWIO kernel."""
    return np.transpose(w, (2, 3, 1, 0))


class _Importer:
    """Tracks which checkpoint keys were consumed; fails loudly on shape or
    coverage mismatches instead of silently fine-tuning random weights."""

    def __init__(self, sd: Mapping[str, np.ndarray]):
        self.sd = dict(sd)
        self.used: set[str] = set()

    def take(self, key: str, expect_shape, transform=None) -> np.ndarray:
        if key not in self.sd:
            raise KeyError(f"pretrained checkpoint is missing {key!r}")
        val = self.sd[key]
        if transform is not None:
            val = transform(val)
        if tuple(val.shape) != tuple(expect_shape):
            raise ValueError(
                f"{key!r}: checkpoint shape {tuple(val.shape)} != model "
                f"shape {tuple(expect_shape)} (after layout transform)"
            )
        self.used.add(key)
        return val

    def unused(self) -> list[str]:
        return sorted(
            k for k in self.sd
            if k not in self.used and not k.endswith("num_batches_tracked")
        )


def _import_bn(imp: _Importer, prefix: str, params: dict, stats: dict) -> None:
    params["scale"] = imp.take(f"{prefix}.weight", params["scale"].shape)
    params["bias"] = imp.take(f"{prefix}.bias", params["bias"].shape)
    stats["mean"] = imp.take(f"{prefix}.running_mean", stats["mean"].shape)
    stats["var"] = imp.take(f"{prefix}.running_var", stats["var"].shape)


def torchvision_resnet_to_flax(
    state_dict: Mapping[str, np.ndarray],
    variables: Mapping[str, Any],
    model_name: str = "resnet50",
) -> dict[str, Any]:
    """Map a torchvision ResNet ``state_dict`` onto Flax ``variables``.

    ``variables`` is the initialised ``{"params": ..., "batch_stats": ...}``
    tree from :class:`~.resnet.ResNet`; the return value has the same
    structure with imported float32 values. The ``head`` kernel/bias import
    only when shapes match (1000-class checkpoint → 1000-class model);
    otherwise they keep their fresh initialisation — the reference's
    "swap fc for num_classes" (``modelling/classification.py:9``).
    """
    if model_name not in _STAGES:
        raise ValueError(
            f"pretrained import supports {sorted(_STAGES)}; got {model_name!r}"
        )
    stage_sizes, block_name = _STAGES[model_name]
    imp = _Importer(state_dict)
    # Deep-copy the tree structure with plain dicts (inputs may be frozen).
    params = jax.tree_util.tree_map(np.asarray, _to_dict(variables["params"]))
    stats = jax.tree_util.tree_map(
        np.asarray, _to_dict(variables["batch_stats"])
    )

    params["conv_init"]["kernel"] = imp.take(
        "conv1.weight", params["conv_init"]["kernel"].shape, _t_conv
    )
    _import_bn(imp, "bn1", params["norm_init"], stats["norm_init"])

    # torchvision Bottleneck/BasicBlock sublayer order == the Flax blocks'
    # compact instantiation order, so conv{k} ↔ Conv_{k-1}, bn{k} ↔
    # BatchNorm_{k-1}, downsample.{0,1} ↔ {conv_proj, norm_proj}.
    n_convs = 3 if block_name == "BottleneckBlock" else 2
    flat = 0
    for stage, count in enumerate(stage_sizes):
        for block in range(count):
            t_prefix = f"layer{stage + 1}.{block}"
            f_name = f"{block_name}_{flat}"
            bp, bs = params[f_name], stats[f_name]
            for k in range(n_convs):
                bp[f"Conv_{k}"]["kernel"] = imp.take(
                    f"{t_prefix}.conv{k + 1}.weight",
                    bp[f"Conv_{k}"]["kernel"].shape,
                    _t_conv,
                )
                _import_bn(
                    imp, f"{t_prefix}.bn{k + 1}",
                    bp[f"BatchNorm_{k}"], bs[f"BatchNorm_{k}"],
                )
            if "conv_proj" in bp:
                bp["conv_proj"]["kernel"] = imp.take(
                    f"{t_prefix}.downsample.0.weight",
                    bp["conv_proj"]["kernel"].shape,
                    _t_conv,
                )
                _import_bn(
                    imp, f"{t_prefix}.downsample.1",
                    bp["norm_proj"], bs["norm_proj"],
                )
            flat += 1

    # Head: torch fc.weight is [out, in]; Flax kernel is [in, out].
    head = params["head"]
    fc_w = state_dict.get("fc.weight")
    if fc_w is not None and fc_w.T.shape == head["kernel"].shape:
        head["kernel"] = imp.take("fc.weight", head["kernel"].shape,
                                  np.transpose)
        head["bias"] = imp.take("fc.bias", head["bias"].shape)
    else:
        # Fresh head (fine-tuning); mark consumed so coverage stays clean.
        imp.used.update(k for k in ("fc.weight", "fc.bias") if k in imp.sd)

    leftover = imp.unused()
    if leftover:
        raise ValueError(
            f"pretrained checkpoint has {len(leftover)} unmapped keys "
            f"(wrong architecture for {model_name}?): {leftover[:8]}..."
        )
    return {"params": params, "batch_stats": stats}


def _to_dict(tree):
    if isinstance(tree, Mapping):
        return {k: _to_dict(v) for k, v in tree.items()}
    return tree
