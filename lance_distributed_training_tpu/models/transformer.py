"""Flax transformer encoder (BERT-base family) — the text arm.

Covers the BASELINE text config ("C4 text → on-device tokenize/pack for
BERT-base"; BASELINE.json configs[3]). The reference itself has no text
models (SURVEY.md §5 "vision classification only") — this extends the task
registry the same way ``modelling/get_model_and_loss.py`` would have.

TPU-first: bf16 compute / f32 params, static shapes (packed fixed-length
sequences from :func:`..data.authoring.create_text_token_dataset`), attention
as batched einsums on the MXU, optional remat for long sequences. The
attention core is factored out (:func:`dot_product_attention`) so the
sequence-parallel ring variant (:mod:`..parallel.ring_attention`) can swap in.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["TransformerEncoder", "bert_base", "bert_small", "gpt_base",
           "gpt_small", "dot_product_attention"]


def dot_product_attention(q, k, v, mask=None, dtype=jnp.bfloat16,
                          causal=False):
    """Standard softmax attention: q,k,v [B, H, S, D] → [B, H, S, D].

    Softmax statistics in f32 for stability; matmuls in ``dtype`` on the MXU.
    ``causal=True`` adds the autoregressive lower-triangular mask (decoder
    attention) on top of any key-validity ``mask``.
    """
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(d).astype(jnp.float32)
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        tri = jnp.tril(jnp.ones((s_q, s_k), bool))[None, None]
        scores = jnp.where(tri, scores, jnp.finfo(jnp.float32).min)
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    weights = nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(dtype), v)


def _accepts_segment_ids(fn) -> bool:
    """Does this attention_fn take the packed-sequence ``segment_ids``
    kwarg (``ops.flash.make_flash_attention`` does; ring attention and the
    plain einsum path express segments as a dense mask instead)?"""
    import inspect

    try:
        return "segment_ids" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


class SelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    causal: bool = False  # decoder (GPT) attention; custom attention_fns
    # must bind their own causality (e.g. make_flash_attention(causal=True))

    @nn.compact
    def __call__(self, x, mask=None, segment_ids=None):
        b, s, h = x.shape
        head_dim = h // self.num_heads
        dense = partial(
            nn.DenseGeneral, dtype=self.dtype, param_dtype=jnp.float32
        )
        q = dense(features=(self.num_heads, head_dim), name="query")(x)
        k = dense(features=(self.num_heads, head_dim), name="key")(x)
        v = dense(features=(self.num_heads, head_dim), name="value")(x)
        # [B, S, H, D] -> [B, H, S, D]
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        attn = self.attention_fn or partial(
            dot_product_attention, dtype=self.dtype, causal=self.causal
        )
        if segment_ids is not None:
            # Only reaches here when the fn declares the kwarg (the
            # encoder lowers segments to a dense block mask otherwise).
            out = attn(q, k, v, mask=mask, segment_ids=segment_ids)
        else:
            out = attn(q, k, v, mask=mask)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, h)
        return dense(features=h, axis=-1, name="out")(out)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    num_experts: int = 0  # >0: switch-MoE MLP instead of dense (expert parallel)
    capacity_factor: float = 1.25
    causal: bool = False

    @nn.compact
    def __call__(self, x, mask=None, segment_ids=None):
        norm = partial(nn.LayerNorm, dtype=self.dtype, param_dtype=jnp.float32)
        y = norm(name="ln_attn")(x)
        y = SelfAttention(self.num_heads, self.dtype,
                          attention_fn=self.attention_fn,
                          causal=self.causal, name="attn")(y, mask,
                                                           segment_ids)
        x = x + y
        y = norm(name="ln_mlp")(x)
        if self.num_experts > 0:
            from .moe import MoEMLP

            y = MoEMLP(self.num_experts, self.mlp_dim,
                       self.capacity_factor, self.dtype, name="moe")(y)
        else:
            y = nn.Dense(self.mlp_dim, dtype=self.dtype,
                         param_dtype=jnp.float32, name="mlp_in")(y)
            y = nn.gelu(y)
            y = nn.Dense(x.shape[-1], dtype=self.dtype,
                         param_dtype=jnp.float32, name="mlp_out")(y)
        return x + y


class TransformerEncoder(nn.Module):
    """Pre-LN BERT-style encoder with an MLM head.

    ``__call__(input_ids, attention_mask, train)`` → logits ``[B, S, vocab]``
    (tied to the input embedding — standard weight tying keeps the head off
    the parameter budget).
    """

    vocab_size: int
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    dtype: Any = jnp.bfloat16
    remat: bool = False
    attention_fn: Optional[Callable] = None
    head: str = "mlm"  # "mlm" → tied vocab logits; "none" → hidden states
    num_experts: int = 0  # >0: MoE MLP on every `moe_every`-th block
    moe_every: int = 2
    capacity_factor: float = 1.25
    causal: bool = False  # decoder-only (GPT) variant: autoregressive mask

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, train: bool = True,
                 segment_ids=None, position_ids=None):
        b, s = input_ids.shape
        embed = nn.Embed(self.vocab_size, self.hidden_size,
                         param_dtype=jnp.float32, name="tok_embed")
        pos_embed = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (self.max_len, self.hidden_size), jnp.float32,
        )
        x = embed(input_ids).astype(self.dtype)
        if position_ids is not None:
            # Packed sequences (the ragged token plane): positions restart
            # per segment, so the embedding gathers at the kernel-emitted
            # intra-sequence offsets instead of the row arange.
            x = x + jnp.take(
                pos_embed, position_ids, axis=0
            ).astype(self.dtype)
        else:
            x = x + pos_embed[:s].astype(self.dtype)

        mask = None
        if attention_mask is not None:
            # [B, S] -> [B, 1, 1, S]: keys masked out, broadcast over queries.
            mask = attention_mask[:, None, None, :].astype(bool)
        seg_kwarg = None
        if segment_ids is not None:
            if self.attention_fn is not None and _accepts_segment_ids(
                self.attention_fn
            ):
                # Segment-native attention (the Pallas flash kernel): pass
                # the ids straight through; they carry validity too.
                seg_kwarg = segment_ids
                mask = None
            else:
                # Dense path: lower segments to the block mask [B,1,S,S] —
                # same-segment-and-live; supersedes the validity mask.
                from ..ops.flash import segment_attention_mask

                mask = segment_attention_mask(segment_ids)

        block = EncoderBlock
        if self.remat:
            block = nn.remat(EncoderBlock, static_argnums=())
        for i in range(self.num_layers):
            # MoE on every moe_every-th block (Switch/GShard convention:
            # alternate dense and expert layers).
            moe_here = (
                self.num_experts > 0 and i % self.moe_every == self.moe_every - 1
            )
            x = block(self.num_heads, self.mlp_dim, self.dtype,
                      attention_fn=self.attention_fn,
                      num_experts=self.num_experts if moe_here else 0,
                      capacity_factor=self.capacity_factor,
                      causal=self.causal,
                      name=f"layer_{i}")(x, mask, seg_kwarg)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=jnp.float32,
                         name="ln_final")(x)
        if self.head == "none":
            return x  # final hidden states [B, S, H] (e.g. the CLIP text tower)
        # Tied MLM head: project back onto the embedding table.
        logits = embed.attend(x.astype(jnp.float32))
        return logits


bert_base = partial(TransformerEncoder, hidden_size=768, num_layers=12,
                    num_heads=12, mlp_dim=3072)
bert_small = partial(TransformerEncoder, hidden_size=256, num_layers=4,
                     num_heads=4, mlp_dim=1024)
# Decoder-only (GPT-style) presets: same trunk, causal attention, tied LM
# head. gpt_base matches GPT-2 124M's shape (768/12/12).
gpt_base = partial(TransformerEncoder, hidden_size=768, num_layers=12,
                   num_heads=12, mlp_dim=3072, causal=True)
gpt_small = partial(TransformerEncoder, hidden_size=256, num_layers=4,
                    num_heads=4, mlp_dim=1024, causal=True)
