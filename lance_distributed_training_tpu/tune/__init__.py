"""Closed-loop pipeline autotuning — the first subsystem that writes BACK
into the pipeline it observes.

The obs/ subsystem (r4) measures every stage of the data plane; this
package closes the loop (ROADMAP open item "self-tuning pipeline"): a
per-process :class:`~.controller.AutoTuner` thread snapshots windowed
deltas of those histograms, attributes the bottleneck (decode-bound vs
transport-bound vs H2D-bound vs train-bound), and actuates live knobs
registered as :class:`~.tunable.Tunable`\\ s — decode worker count
(``WorkerPool.resize``), prefetch depth (all loaders), buffer-pool page
budget, placement ring depth, fleet stripe width. Actuation changes
*capacity*, never content: the batch stream stays bit-identical in value
and order through any decision (pinned by the parity tests +
``bench_autotune.py``), and ``--no_autotune`` runs the exact fixed-knob
pipeline of r8 and earlier.

Decisions are deterministic and testable: set ``LDT_AUTOTUNE_TRACE=<path>``
and every tick's (window, knobs, bounds, decisions) lands in a JSONL trace
that :func:`~.controller.verify_trace` replays against a fresh policy.

The fleet half lives in ``fleet/``: DataServices report windowed pressure
in heartbeats, the Coordinator aggregates it into a scale-up/drain
recommendation on ``/metrics`` + ``/healthz`` + ``ldt fleet recommend``.
"""

from .controller import (  # noqa: F401
    TRACE_ENV,
    AutoTuner,
    derive_window,
    replay_trace,
    verify_trace,
)
from .policy import (  # noqa: F401
    BOTTLENECK_CODES,
    Decision,
    HillClimbPolicy,
    PolicyConfig,
)
from .tunable import AdjustableQueue, Tunable, collect_tunables  # noqa: F401

__all__ = [
    "AutoTuner",
    "AdjustableQueue",
    "BOTTLENECK_CODES",
    "Decision",
    "HillClimbPolicy",
    "PolicyConfig",
    "TRACE_ENV",
    "Tunable",
    "collect_tunables",
    "derive_window",
    "replay_trace",
    "verify_trace",
]
