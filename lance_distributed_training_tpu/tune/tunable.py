"""``Tunable`` — the registration surface between pipeline knobs and the
autotuner.

Every live knob the controller may actuate (decode worker count, prefetch
depth, buffer-pool page budget, placement ring depth, fleet stripe width) is
exposed by its owning component as a :class:`Tunable`: a name, a getter, a
setter, and **mandatory** ``lo``/``hi`` bounds. Bounds are not optional by
design — an autotuner with an unbounded actuator is how a controller melts
a host (grow-on-stall against a saturated disk grows forever) — and the
LDT1101 lint enforces that every ``Tunable(...)`` construction site in the
package declares both.

Components expose their knobs via a ``tunables() -> list[Tunable]`` method
(``WorkerPool``, ``DataPipeline``, ``MapStylePipeline``, ``RemoteLoader``,
``FleetLoader``, ``BufferPool``, ``PlacementPlane``, ``PlacedLoader`` — and
since r16 ``LoaderGraph``, the graph root whose single ``tunables()``
aggregation is what the trainer registers); the trainer gathers them with
:func:`collect_tunables` and hands the set to the
:class:`~.controller.AutoTuner`. Nothing registers globally: with
``--no_autotune`` no Tunable is ever constructed and the pipeline runs the
exact fixed-knob configuration it always did.

:class:`AdjustableQueue` is the mechanism behind the prefetch/ring-depth
actuators: a bounded ``queue.Queue`` whose ``maxsize`` can be changed while
producers and consumers are live. Growing notifies blocked producers;
shrinking just lets the excess drain (puts block until the backlog is below
the new bound) — items are never dropped, so actuation can never reorder or
lose a batch.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, List

__all__ = ["Tunable", "AdjustableQueue", "collect_tunables"]


class Tunable:
    """One live integer knob with hard actuation bounds.

    ``getter()`` returns the current value; ``setter(v)`` applies a new one
    and may return the value actually applied (clamping happens here anyway,
    so setters can be plain attribute writes). ``set`` is what the
    controller calls; it clamps to ``[lo, hi]`` and returns the applied
    value, so a policy can observe that its request hit a bound.
    """

    def __init__(
        self,
        name: str,
        getter: Callable[[], int],
        setter: Callable[[int], object],
        *,
        lo: int,
        hi: int,
        doc: str = "",
    ):
        lo, hi = int(lo), int(hi)
        if lo >= hi:
            raise ValueError(
                f"tunable {name!r} needs lo < hi, got [{lo}, {hi}] — a "
                "degenerate range means the knob is not tunable; don't "
                "register it"
            )
        self.name = str(name)
        self.lo = lo
        self.hi = hi
        self.doc = doc
        self._getter = getter
        self._setter = setter

    def get(self) -> int:
        return int(self._getter())

    def set(self, value: int) -> int:
        """Clamp to ``[lo, hi]``, actuate, return the applied value."""
        value = min(self.hi, max(self.lo, int(value)))
        applied = self._setter(value)
        return int(applied) if applied is not None else value

    def __repr__(self) -> str:  # debugging/`ldt fleet`-style dumps
        return (
            f"Tunable({self.name!r}, value={self.get()}, "
            f"lo={self.lo}, hi={self.hi})"
        )


def collect_tunables(*components) -> List[Tunable]:
    """Gather every component's ``tunables()`` into one list, first
    registration of a name wins (a ``PlacedLoader`` wrapping a
    ``FleetLoader`` yields the plane's knobs before the inner loader's, and
    an eval loader built later must not steal the train loader's names).
    ``None`` components and components without a ``tunables`` method are
    skipped, so callers can pass whatever the config happened to build."""
    out: List[Tunable] = []
    seen: set = set()
    for c in components:
        if c is None:
            continue
        fn = getattr(c, "tunables", None)
        if fn is None:
            continue
        for t in fn():
            if t.name not in seen:
                seen.add(t.name)
                out.append(t)
    return out


class AdjustableQueue(queue.Queue):
    """Bounded queue whose bound can move while threads are blocked on it.

    The live half of the prefetch/ring-depth actuators: ``set_maxsize``
    takes the queue's own mutex, so it serializes correctly against
    concurrent ``put``/``get``, and notifies ``not_full`` so producers
    blocked against the OLD bound wake up immediately when the bound grows.
    Shrinking never drops items: the backlog above the new bound drains
    through the consumer while further puts block — the stream stays intact
    and ordered through any actuation.

    Always bounded: the constructor and ``set_maxsize`` clamp to >= 1
    (``maxsize=0`` is stdlib for *infinite*, which would void the
    backpressure contract LDT202 exists to protect).
    """

    def __init__(self, maxsize: int):
        super().__init__(maxsize=max(1, int(maxsize)))

    def set_maxsize(self, maxsize: int) -> int:
        with self.mutex:
            self.maxsize = max(1, int(maxsize))
            # Wake every blocked producer: with a grown bound several puts
            # may now proceed, and a notify_all costs nothing here (resize
            # is a control-plane event, not a hot-path one).
            self.not_full.notify_all()
            return self.maxsize


class _LiveQueues:
    """Tiny holder a pipeline shares between its iterating thread (which
    installs the epoch's live queues) and a controller thread calling
    ``set_prefetch`` — one lock so install/adjust/clear never interleave."""

    def __init__(self):
        self._lock = threading.Lock()
        self._queues: List[AdjustableQueue] = []

    def install(self, queues: Iterable[AdjustableQueue]) -> None:
        with self._lock:
            self._queues = list(queues)

    def clear(self) -> None:
        with self._lock:
            self._queues = []

    def resize_total(self, depth: int) -> None:
        """Split ``depth`` across the live queues (ceil-divided, min 1 each
        — the multi-producer pipeline's total-buffered-depth convention)."""
        with self._lock:
            qs = list(self._queues)
        if not qs:
            return
        per = max(1, -(-max(1, int(depth)) // len(qs)))
        for q in qs:
            q.set_maxsize(per)
