"""``AutoTuner`` — the closed loop between obs/ and the pipeline's knobs.

A background daemon thread per training process: every ``interval_s`` it

1. pulls the windowed delta of the process registry
   (:class:`~..obs.registry.RegistryDelta` — the obs subsystem already
   measures everything the tf.data autotuner needs: decode_ms, queue_wait,
   batch_age, stall pct, bufpool hit rate, shm ring waits),
2. reduces it to a small signal ``window`` (:func:`derive_window`),
3. asks the :class:`~.policy.HillClimbPolicy` for decisions, and
4. actuates them through the registered :class:`~.tunable.Tunable` set —
   clamped to each knob's declared bounds, never reordering or dropping a
   batch (every actuator adjusts *capacity*, not content).

Observability: every tick lands in ``autotune_ticks_total``; every applied
actuation in ``autotune_decisions_total`` (+ ``autotune_reverts_total`` for
reverts), updates the ``autotune_knob_<name>`` gauge, sets
``autotune_bottleneck`` (see :data:`~.policy.BOTTLENECK_CODES`), and emits
an ``autotune.apply`` span — so ``/metrics`` and ``ldt trace export`` both
show what the controller did and why.

Determinism (``LDT_AUTOTUNE_TRACE=<path>``): each tick appends one JSONL
record ``{tick, window, knobs, bounds, decisions}``. The policy is a pure
function of its state and those inputs, so :func:`replay_trace` can re-run
a recorded sequence against a fresh policy and :func:`verify_trace` asserts
the identical decision sequence comes out — decisions are testable after
the fact, not just observable.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..obs.registry import MetricsRegistry, RegistryDelta, default_registry
from ..obs.spans import span
from .policy import BOTTLENECK_CODES, Decision, HillClimbPolicy, PolicyConfig
from .tunable import Tunable

__all__ = [
    "AutoTuner",
    "derive_window",
    "replay_trace",
    "verify_trace",
    "TRACE_ENV",
]

TRACE_ENV = "LDT_AUTOTUNE_TRACE"

# Decode-latency sources, first present wins: in-process pipelines stamp
# pipeline_decode_ms, remote loaders close lineage_decode_ms, the service
# host observes svc_decode_ms (a loopback process can have all three).
_DECODE_SOURCES = ("pipeline_decode_ms", "lineage_decode_ms", "svc_decode_ms")


def derive_window(delta: Dict[str, float]) -> Dict[str, float]:
    """Reduce one registry delta to the policy's signal dict. Keys are
    omitted (not zeroed) when their source series saw no traffic, so the
    policy can distinguish "no pool in this run" from "pool hit rate 0".

    * ``steps`` — train steps this window,
    * ``stall_pct`` — loader share of (loader + step) busy time,
    * ``h2d_pct`` — H2D dispatch share of the same denominator,
    * ``bufpool_hit_rate`` — window hit/(hit+miss),
    * ``decode_ms_p95`` / ``queue_wait_ms_p95`` / ``shm_wait_ms_p95`` —
      tail latencies per stage,
    * ``ring_occupancy`` — the placement ring's current depth gauge.
    """
    w: Dict[str, float] = {}
    steps = delta.get("trainer_step_ms_count", 0.0)
    w["steps"] = steps
    loader_ms = delta.get("trainer_loader_ms_sum", 0.0)
    step_ms = delta.get("trainer_step_ms_sum", 0.0)
    busy = loader_ms + step_ms
    w["stall_pct"] = 100.0 * loader_ms / busy if busy > 0 else 0.0
    h2d_ms = delta.get("trainer_h2d_ms_sum", 0.0)
    w["h2d_pct"] = 100.0 * h2d_ms / busy if busy > 0 else 0.0
    hits = delta.get("bufpool_hit_total", 0.0)
    misses = delta.get("bufpool_miss_total", 0.0)
    if hits + misses > 0:
        w["bufpool_hit_rate"] = hits / (hits + misses)
    for source in _DECODE_SOURCES:
        p95 = delta.get(f"{source}_p95")
        if p95 is not None:
            w["decode_ms_p95"] = p95
            # Straggler signal: tail-to-median skew of the SAME decode
            # series. Near 1 = uniform item costs (more capacity is the
            # only lever); large = a few items pin batch assembly — the
            # straggler_bound rung grows sched_lookahead instead.
            p50 = delta.get(f"{source}_p50")
            if p50 is not None and p50 > 0:
                w["decode_skew"] = p95 / p50
            break
    # Device-decode split attribution (the --device_decode arm): the host
    # entropy half's share of the per-batch decode cost. Near 1.0 = the
    # host Huffman pass dominates (more decode workers still pay off);
    # near 0.0 = the jitted device kernel dominates (growing the worker
    # pool buys nothing — the policy skips that rung). Present only when
    # both series saw traffic this window — which means IN-PROCESS decode:
    # registries are process-local, so with a WorkerPool (num_workers>0)
    # or a remote data service the entropy histogram lands in the decoding
    # process and the signal is absent here; the policy then falls back to
    # the plain capacity ladder (cross-process metric forwarding is the
    # open item, same locality as the server-side svc_* series).
    entropy_p50 = delta.get("decode_entropy_ms_p50")
    device_p50 = delta.get("decode_device_ms_p50")
    if entropy_p50 is not None and device_p50 is not None:
        total = entropy_p50 + device_p50
        if total > 0:
            w["decode_split"] = entropy_p50 / total
    # Ragged token plane (the --token_pack arm AND its padded control):
    # padding waste as a live signal. payload = real tokens, grid = the
    # token grid the device actually processes; their window ratio is what
    # the pack policy rung trades against recompile count. Same process-
    # locality caveat as decode_split: the counters live in the DECODING
    # process.
    payload = delta.get("pack_payload_tokens_total", 0.0)
    grid_tokens = delta.get("pack_grid_tokens_total", 0.0)
    if grid_tokens > 0:
        w["pad_waste_pct"] = 100.0 * (grid_tokens - payload) / grid_tokens
        w["pack_occupancy"] = payload / grid_tokens
    new_shapes = delta.get("pack_new_shapes_total")
    if new_shapes is not None:
        # Fresh jit traces the pack transform paid this window (each is a
        # compile): the cost side of a finer rows quantum. Lives in the
        # TRAINER process (the transform runs there), so it is present
        # even when decode is remote.
        w["pack_new_shapes"] = new_shapes
    # Straggler scheduler (data/schedule.py): dispatch reorders this
    # window. Present only when a scheduler ran — lets the policy (and
    # `ldt trace` readers) tell "scheduler off" from "scheduler idle".
    sched = delta.get("sched_dispatch_reorders_total")
    if sched is not None:
        w["sched_reorders"] = sched
    queue_wait = delta.get("svc_queue_wait_ms_p95")
    if queue_wait is not None:
        w["queue_wait_ms_p95"] = queue_wait
    shm_wait = delta.get("shm_slot_wait_ms_p95")
    if shm_wait is not None:
        w["shm_wait_ms_p95"] = shm_wait
    ring = delta.get("placement_buffer_depth")
    if ring is not None:
        w["ring_occupancy"] = ring
    jobs_active = delta.get("svc_jobs_active")
    if jobs_active is not None:
        # Job plane (r20): how many tenants share this data plane right
        # now. Present only on a process that hosts a DataService (the
        # gauge is server-side) — lets the policy distinguish "my stall
        # is my own" from "capacity is deliberately shared N ways", where
        # shrinking a knob would hand the freed capacity to OTHER jobs
        # rather than prove it unneeded.
        w["jobs_active"] = jobs_active
    return w


class AutoTuner:
    """Own the control loop: a daemon thread ticking every ``interval_s``.

    ``tunables`` may be empty at construction and swapped per epoch with
    :meth:`set_tunables` (the trainer rebuilds loaders each epoch; the
    controller outlives them). :meth:`tick` is public and synchronous — the
    tests and the bench drive single deterministic control steps through it
    without any thread.
    """

    def __init__(
        self,
        tunables: Optional[List[Tunable]] = None,
        *,
        registry: Optional[MetricsRegistry] = None,
        interval_s: float = 1.0,
        policy: Optional[HillClimbPolicy] = None,
        policy_config: Optional[PolicyConfig] = None,
        trace_path: Optional[str] = None,
    ):
        self.registry = registry if registry is not None else default_registry()
        self.interval_s = max(0.05, float(interval_s))
        self.policy = (
            policy if policy is not None
            else HillClimbPolicy(policy_config)
        )
        self._delta = RegistryDelta(self.registry)
        self._lock = threading.Lock()  # guards _tunables + trace file + tick
        self._tunables: Dict[str, Tunable] = {}
        if tunables:
            self.set_tunables(tunables)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tick_n = 0
        self._ticks = self.registry.counter("autotune_ticks_total")
        self._decisions = self.registry.counter("autotune_decisions_total")
        self._reverts = self.registry.counter("autotune_reverts_total")
        self._errors = self.registry.counter("autotune_errors_total")
        self._bottleneck = self.registry.gauge("autotune_bottleneck")
        self._trace_file = None
        path = trace_path if trace_path is not None else os.environ.get(
            TRACE_ENV
        )
        if path:
            # Append (a resumed run extends the trace); line-buffered JSONL
            # so a crash mid-run still leaves complete records behind.
            self._trace_file = open(path, "a", buffering=1)

    # -- tunable set --------------------------------------------------------

    def set_tunables(self, tunables: List[Tunable]) -> None:
        """Swap the registered knob set (per-epoch loader rebuilds). First
        occurrence of a name wins, matching
        :func:`~.tunable.collect_tunables`."""
        table: Dict[str, Tunable] = {}
        for t in tunables:
            table.setdefault(t.name, t)
        with self._lock:
            self._tunables = table
        for name, t in table.items():
            self.registry.gauge(f"autotune_knob_{name}").set(t.get())

    # -- one control step ---------------------------------------------------

    def tick(self) -> List[Decision]:
        """One synchronous control step: window → decide → actuate.
        Returns the applied decisions (after bound clamping; a decision
        whose clamped target equals the current value is dropped as a
        no-op, not counted, not actuated)."""
        with self._lock:
            return self._tick_locked()

    def _tick_locked(self) -> List[Decision]:
        self._tick_n += 1
        self._ticks.inc()
        window = derive_window(self._delta.delta())
        tunables = self._tunables
        knobs = {name: t.get() for name, t in tunables.items()}
        bounds = {name: (t.lo, t.hi) for name, t in tunables.items()}
        decisions = self.policy.decide(window, knobs, bounds)
        applied: List[Decision] = []
        for d in decisions:
            t = tunables.get(d.knob)
            if t is None:
                continue
            target = min(t.hi, max(t.lo, int(d.target)))
            if target == knobs[d.knob]:
                continue  # clamped into a no-op: nothing to actuate
            with span("autotune.apply", knob=d.knob, target=target,
                      reason=d.reason):
                value = t.set(target)
            applied.append(Decision(d.knob, value, d.reason))
            self._decisions.inc()
            if d.reason == "revert":
                self._reverts.inc()
            self.registry.gauge(f"autotune_knob_{d.knob}").set(value)
        self._bottleneck.set(
            BOTTLENECK_CODES.get(self.policy.last_bottleneck, 0)
        )
        if self._trace_file is not None:
            record = {
                "tick": self._tick_n,
                "window": {k: round(float(v), 6)
                           for k, v in window.items()},
                "knobs": knobs,
                "bounds": {k: list(v) for k, v in bounds.items()},
                "decisions": [
                    [d.knob, d.target, d.reason] for d in decisions
                ],
                "applied": [
                    [d.knob, d.target, d.reason] for d in applied
                ],
            }
            self._trace_file.write(json.dumps(record) + "\n")
        return applied

    # -- lifecycle ----------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 — an actuator failure
                # (a resize hitting OSError under fd pressure, a knob whose
                # component died) must not silently kill the controller for
                # the rest of the run — a stuck-at-bad-knobs run is exactly
                # what this subsystem exists to prevent. Count it (the
                # autotune_errors_total series is the operator's signal),
                # log once per error, keep ticking.
                self._errors.inc()
                print(f"[autotune] tick failed: {exc!r}", flush=True)

    def start(self) -> "AutoTuner":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ldt-autotune"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        with self._lock:
            if self._trace_file is not None:
                self._trace_file.close()
                self._trace_file = None

    def __enter__(self) -> "AutoTuner":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- trace replay ------------------------------------------------------------


def read_trace(path: str) -> List[dict]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


def replay_trace(
    path: str, policy_config: Optional[PolicyConfig] = None
) -> List[List[Tuple[str, int, str]]]:
    """Re-run a fresh policy over a recorded trace's (window, knobs,
    bounds) sequence; returns the replayed decision lists in trace order.
    The policy is deterministic, so this must equal the recorded
    ``decisions`` — :func:`verify_trace` is that assertion."""
    policy = HillClimbPolicy(policy_config)
    out: List[List[Tuple[str, int, str]]] = []
    for record in read_trace(path):
        decisions = policy.decide(
            record["window"],
            {k: int(v) for k, v in record["knobs"].items()},
            {k: (int(v[0]), int(v[1]))
             for k, v in record["bounds"].items()},
        )
        out.append([(d.knob, d.target, d.reason) for d in decisions])
    return out


def verify_trace(
    path: str, policy_config: Optional[PolicyConfig] = None
) -> Tuple[bool, List[int]]:
    """``(ok, mismatched_tick_numbers)`` — replay vs record, tick by
    tick."""
    records = read_trace(path)
    replayed = replay_trace(path, policy_config)
    mismatches = []
    for record, decisions in zip(records, replayed):
        recorded = [tuple(d) for d in record["decisions"]]
        if recorded != decisions:
            mismatches.append(record["tick"])
    return not mismatches, mismatches
