"""Hill-climbing autotune policy — gradient-free, hysteretic, deterministic.

The controller (:mod:`.controller`) reduces each windowed registry delta to
a small ``window`` dict (steps, stall_pct, h2d_pct, bufpool_hit_rate,
decode/queue-wait percentiles); this module owns the *decision function*:

    decide(window, knobs, bounds) -> [Decision, ...]

``decide`` is a pure function of the policy's internal state and its
arguments — no clocks, no randomness, no registry reads — which is what
makes ``LDT_AUTOTUNE_TRACE`` replay possible: feed the recorded
(window, knobs, bounds) sequence to a fresh policy and the identical
decision sequence must come out (pinned by ``tests/test_tune.py``).

The shape is tf.data's autotuner translated to this pipeline's knobs
(PAPERS.md, arxiv 2101.12127 — hill climbing over parallelism/prefetch with
hysteresis, not a model), with MinatoLoader's lesson (2509.10712) that the
same stall signals drive adaptation when per-item cost varies:

* **attribution first** — a high loader stall is classified before any knob
  moves: H2D-bound (h2d share of busy time high) grows the placement ring;
  pool-bound (bufpool hit rate collapsed) grows the page budget;
  otherwise decode/transport-bound walks the capacity ladder
  ``workers → stripe_width → prefetch`` (more decode processes, more fleet
  members striped, deeper prefetch — in order of expected payoff).
* **hysteresis** — grow only above ``stall_hi_pct``, consider shrinking
  only after ``shrink_patience`` consecutive windows below
  ``stall_lo_pct``; the band between is deliberately dead.
* **cooldown** — after any actuation the policy sits out
  ``cooldown_ticks`` windows so the change can show up in the signal
  before the next move (a controller reacting to its own transient is the
  classic oscillation failure).
* **revert** — the first evaluated window after an actuation is compared
  to the window that triggered it; if stall worsened by more than
  ``revert_margin_pct`` points the knob goes back and is blocked for
  ``blocked_ticks`` windows (hill climbing needs a way back down).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

__all__ = ["Decision", "PolicyConfig", "HillClimbPolicy", "BOTTLENECK_CODES"]

# Bottleneck attribution → the code the autotune_bottleneck gauge carries
# (a gauge must be a number; the glossary in README maps it back).
BOTTLENECK_CODES = {
    "none": 0,
    "decode_bound": 1,
    "transport_bound": 2,
    "h2d_bound": 3,
    "pool_bound": 4,
    "train_bound": 5,
    # --device_decode runs: stalled while the device transform (not the
    # host entropy half) dominates per-batch decode — more decode workers
    # cannot help, the ladder skips that rung.
    "device_transform_bound": 6,
    # --token_pack runs: the packed grid carries too much dead padding —
    # tighten the row-count quantum (finer rounding, more shapes).
    "pad_waste_bound": 7,
    # --token_pack runs: the pack transform is paying fresh jit traces
    # every window — coarsen the quantum (fewer shapes, more padding).
    "recompile_bound": 8,
    # Stalled while per-item decode cost is heavily skewed (p95/p50 of
    # the decode series high): a few stragglers pin batch assembly —
    # grow the scheduler's dispatch-reorder lookahead before throwing
    # uniform capacity at a non-uniform problem.
    "straggler_bound": 9,
    # Multi-tenant data plane (r20): calm window, but >1 jobs share this
    # server's produce capacity — shrink is withheld (the fair scheduler
    # would hand the freed capacity to other jobs, so calm proves no
    # headroom of our own).
    "multi_tenant_hold": 10,
}

# Capacity ladder for decode/transport-bound growth, in expected-payoff
# order: more decode processes first, then more fleet members striped, then
# deeper prefetch (prefetch only papers over variance once throughput is
# actually matched). Only knobs present in the run's tunable set are
# considered.
_GROW_LADDER = ("workers", "stripe_width", "prefetch")
# Shrink order when train-bound: cheapest-to-give-back first.
_SHRINK_LADDER = (
    "prefetch", "workers", "stripe_width", "ring_depth", "bufpool_pages",
)


@dataclasses.dataclass(frozen=True)
class Decision:
    """One actuation: set ``knob`` to ``target`` because ``reason``."""

    knob: str
    target: int
    reason: str


@dataclasses.dataclass
class PolicyConfig:
    """Thresholds — all hysteresis bands and patience counters in one
    place so a trace header can pin them for replay."""

    stall_hi_pct: float = 30.0  # grow above this loader stall
    stall_lo_pct: float = 5.0  # shrink candidate below this
    h2d_hi_pct: float = 15.0  # H2D share of busy time that means H2D-bound
    hit_rate_lo: float = 0.6  # bufpool hit rate that means pool-bound
    min_steps: int = 2  # windows with fewer train steps carry no signal
    cooldown_ticks: int = 2  # sit-out windows after any actuation
    shrink_patience: int = 6  # calm windows before giving capacity back
    revert_margin_pct: float = 10.0  # stall worsening that reverts a move
    revert_patience: int = 2  # consecutive worsened windows before the
    # revert fires — a heavyweight actuation (worker respawn) shows a
    # transient stall spike in its first window; one clean window clears
    # the verdict (reacting to the transient is the classic oscillation)
    blocked_ticks: int = 8  # windows a reverted knob stays off-limits
    decode_split_lo: float = 0.35  # --device_decode attribution: when the
    # host entropy share of decode falls below this, the bottleneck is the
    # device kernel, not host decode — the capacity ladder skips the
    # workers rung (spawning decode processes cannot move a device-bound
    # stall; the prefetch/stripe rungs still apply)
    pad_waste_hi: float = 30.0  # --token_pack: dead-token share of the
    # packed grid above which the pack rung tightens pack_rows_quantum
    # (finer row rounding = less waste, more compiled shapes). Evaluated
    # only OUTSIDE the stalled band — padding waste is a FLOP tax, not a
    # stall, and the capacity rungs keep priority when the loader starves.
    recompile_hi: float = 3.0  # --token_pack: fresh pack-transform jit
    # traces per window above which the rung coarsens pack_rows_quantum
    # (the opposite trade). Steady state sees 0 new shapes per window.
    decode_skew_hi: float = 4.0  # straggler attribution: decode-latency
    # tail-to-median ratio (decode_skew = p95/p50) above which a stall
    # is straggler_bound — a few heavy items pin assembly, so the
    # scheduler's sched_lookahead rung fires before the capacity ladder
    # (growing workers adds uniform capacity; a skewed stall needs
    # reordered dispatch). Uniform corpora sit near 1-2; the skewed
    # bench corpus clears 4 comfortably.

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _grow(value: int, hi: int) -> int:
    """Multiplicative-ish climb: 1→2→4→8 … (a decode pool at 1 worker on a
    97%-stalled host needs to move in doublings, not +1 crawls), capped."""
    return min(hi, max(value + 1, value * 2))


class HillClimbPolicy:
    """Stateful but deterministic: state evolves only through
    :meth:`decide` calls, each a pure function of its arguments."""

    def __init__(self, config: Optional[PolicyConfig] = None):
        self.config = config if config is not None else PolicyConfig()
        self.last_bottleneck = "none"
        self._cooldown = 0
        self._calm = 0
        # (knob, previous value, stall_pct at decision time, consecutive
        # worsened windows seen) — judged on post-cooldown signal windows;
        # None when nothing is pending.
        self._pending: Optional[Tuple[str, int, float, int]] = None
        self._blocked: Dict[str, int] = {}  # knob -> windows remaining

    # -- helpers -----------------------------------------------------------

    def _tick_blocked(self) -> None:
        for knob in list(self._blocked):
            self._blocked[knob] -= 1
            if self._blocked[knob] <= 0:
                del self._blocked[knob]

    def _growable(self, knob: str, knobs: Dict[str, int],
                  bounds: Dict[str, Tuple[int, int]]) -> bool:
        return (
            knob in knobs
            and knob not in self._blocked
            and knobs[knob] < bounds.get(knob, (1, knobs[knob]))[1]
        )

    def _shrinkable(self, knob: str, knobs: Dict[str, int],
                    bounds: Dict[str, Tuple[int, int]]) -> bool:
        return (
            knob in knobs
            and knob not in self._blocked
            and knobs[knob] > bounds.get(knob, (knobs[knob], knobs[knob]))[0]
        )

    def _act(self, knob: str, target: int, reason: str,
             stall: float, knobs: Dict[str, int]) -> List[Decision]:
        self._pending = (knob, knobs[knob], stall, 0)
        self._cooldown = self.config.cooldown_ticks
        return [Decision(knob, target, reason)]

    # -- the decision function ---------------------------------------------

    def decide(
        self,
        window: Dict[str, float],
        knobs: Dict[str, int],
        bounds: Dict[str, Tuple[int, int]],
    ) -> List[Decision]:
        """``window``: the controller's derived signals. ``knobs``: current
        value per registered tunable. ``bounds``: (lo, hi) per tunable.
        Returns the actuations for this window (usually zero or one)."""
        c = self.config
        self._tick_blocked()
        steps = window.get("steps", 0.0)
        if steps < c.min_steps:
            # No traffic, no signal — also freezes cooldown/patience so a
            # paused trainer doesn't age the controller's state.
            return []
        stall = window.get("stall_pct", 0.0)
        if self._cooldown > 0:
            self._cooldown -= 1
            return []
        if self._pending is not None:
            knob, prev_value, prev_stall, worse = self._pending
            if (
                stall > prev_stall + c.revert_margin_pct
                and knob in knobs
                and knobs[knob] != prev_value
            ):
                worse += 1
                if worse >= c.revert_patience:
                    # Persistently worse: back off and block the knob so
                    # the climb explores elsewhere.
                    self._pending = None
                    self._blocked[knob] = c.blocked_ticks
                    self._cooldown = c.cooldown_ticks
                    self.last_bottleneck = "none"
                    return [Decision(knob, prev_value, "revert")]
                # Could be the actuation's own transient (a worker respawn
                # stalls its first window): hold the verdict, act on
                # nothing until it resolves.
                self._pending = (knob, prev_value, prev_stall, worse)
                return []
            # One clean window acquits the move.
            self._pending = None
        if stall >= c.stall_hi_pct:
            self._calm = 0
            h2d = window.get("h2d_pct", 0.0)
            if h2d >= c.h2d_hi_pct and self._growable(
                "ring_depth", knobs, bounds
            ):
                self.last_bottleneck = "h2d_bound"
                return self._act(
                    "ring_depth",
                    _grow(knobs["ring_depth"], bounds["ring_depth"][1]),
                    "h2d_bound", stall, knobs,
                )
            hit_rate = window.get("bufpool_hit_rate")
            if (
                hit_rate is not None
                and hit_rate < c.hit_rate_lo
                and self._growable("bufpool_pages", knobs, bounds)
            ):
                self.last_bottleneck = "pool_bound"
                return self._act(
                    "bufpool_pages",
                    _grow(knobs["bufpool_pages"],
                          bounds["bufpool_pages"][1]),
                    "pool_bound", stall, knobs,
                )
            skew = window.get("decode_skew", 0.0)
            if skew >= c.decode_skew_hi and self._growable(
                "sched_lookahead", knobs, bounds
            ):
                # Straggler rung: a skewed decode tail means a FEW items
                # pin assembly — widen the scheduler's dispatch-reorder
                # window before the uniform-capacity ladder (more workers
                # cannot move a stall caused by one heavy item at the
                # head of the line).
                self.last_bottleneck = "straggler_bound"
                return self._act(
                    "sched_lookahead",
                    _grow(knobs["sched_lookahead"],
                          bounds["sched_lookahead"][1]),
                    "straggler_bound", stall, knobs,
                )
            device_bound = (
                window.get("decode_split", 1.0) < c.decode_split_lo
            )
            for knob in _GROW_LADDER:
                if knob == "workers" and device_bound:
                    # decode_split attribution: the device kernel, not the
                    # host entropy half, owns the decode cost — a bigger
                    # worker pool cannot move this stall. Skip to the
                    # transport rungs.
                    continue
                if self._growable(knob, knobs, bounds):
                    reason = (
                        "decode_bound" if knob == "workers"
                        else "device_transform_bound" if device_bound
                        else "transport_bound"
                    )
                    self.last_bottleneck = reason
                    return self._act(
                        knob, _grow(knobs[knob], bounds[knob][1]),
                        reason, stall, knobs,
                    )
            # Stalled with every knob at its ceiling (or blocked): nothing
            # left to actuate — the fleet half's scale-up recommendation is
            # the next lever (Coordinator pressure aggregation).
            self.last_bottleneck = (
                "device_transform_bound" if device_bound else "decode_bound"
            )
            return []
        # Pack rung (--token_pack, outside the stalled band): trade the
        # packed row-count quantum between padding waste (a FLOP tax the
        # stall signal never sees) and recompile churn. Same hysteresis/
        # cooldown/revert machinery as every other knob — _act arms the
        # pending-revert judgment and the cooldown sit-out.
        if "pack_rows_quantum" in knobs:
            shapes = window.get("pack_new_shapes", 0.0)
            if shapes >= c.recompile_hi and self._growable(
                "pack_rows_quantum", knobs, bounds
            ):
                self._calm = 0
                self.last_bottleneck = "recompile_bound"
                return self._act(
                    "pack_rows_quantum",
                    _grow(knobs["pack_rows_quantum"],
                          bounds["pack_rows_quantum"][1]),
                    "recompile_bound", stall, knobs,
                )
            waste = window.get("pad_waste_pct")
            if (
                waste is not None
                and waste >= c.pad_waste_hi
                and self._shrinkable("pack_rows_quantum", knobs, bounds)
            ):
                self._calm = 0
                self.last_bottleneck = "pad_waste_bound"
                return self._act(
                    "pack_rows_quantum",
                    max(bounds["pack_rows_quantum"][0],
                        knobs["pack_rows_quantum"] // 2),
                    "pad_waste_bound", stall, knobs,
                )
        if stall <= c.stall_lo_pct:
            if window.get("jobs_active", 0) > 1:
                # Multi-tenant data plane (r20): this process looks calm,
                # but the capacity a shrink would "give back" is shared —
                # the fair scheduler hands it to the OTHER jobs, so a calm
                # window proves nothing about this job's own headroom.
                # Hold every knob instead of ratcheting down (windows with
                # no jobs_active signal — no DataService in-process —
                # keep the exact pre-r20 shrink behavior).
                self._calm = 0
                self.last_bottleneck = "multi_tenant_hold"
                return []
            self._calm += 1
            if self._calm >= c.shrink_patience:
                self._calm = 0
                for knob in _SHRINK_LADDER:
                    if self._shrinkable(knob, knobs, bounds):
                        self.last_bottleneck = "train_bound"
                        return self._act(
                            knob, knobs[knob] - 1,
                            "train_bound", stall, knobs,
                        )
            else:
                self.last_bottleneck = "train_bound"
            return []
        # Dead band: healthy, leave everything alone.
        self._calm = 0
        self.last_bottleneck = "none"
        return []
