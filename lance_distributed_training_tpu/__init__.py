"""lance_distributed_training_tpu — a TPU-native distributed data-loading +
data-parallel training framework.

Re-design (NOT a port) of ``lancedb/lance-distributed-training`` for TPU:

* a Lance-isomorphic fragmented columnar store (:mod:`.data.format`) replacing
  the upstream ``pylance`` Rust core the reference depends on,
* sampler *plans* — pure functions over fragment row-counts
  (:mod:`.data.samplers`) — replacing ``ShardedBatchSampler`` /
  ``ShardedFragmentSampler`` / ``FullScanSampler``,
* a prefetching input pipeline that materialises **global** ``jax.Array``
  batches with a ``NamedSharding`` over a device mesh (:mod:`.data.pipeline`)
  instead of per-rank torch tensors,
* one mesh-aware trainer (:mod:`.trainer`) replacing the reference's four
  near-identical torchrun driver scripts (``lance_iterable.py``,
  ``lance_map_style.py``, ``torch_version/{iter,map}_style.py``),
* a Flax model zoo + task registry (:mod:`.models`) replacing
  ``modelling/get_model_and_loss.py``.

Gradient synchronisation is sharding-propagated inside a jitted step function
(XLA collectives over ICI/DCN) — the TPU-native equivalent of the reference's
``torch.nn.parallel.DistributedDataParallel`` + NCCL.
"""

__version__ = "0.1.0"

from . import data, models, ops, parallel, service, utils  # noqa: F401
from .trainer import TrainConfig, train  # noqa: E402,F401  (the public API)
