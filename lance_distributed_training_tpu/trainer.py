"""One mesh-aware trainer — replaces the reference's four driver scripts.

The reference duplicates a near-identical DDP loop across
``lance_iterable.py:74-132``, ``lance_map_style.py:46-126``,
``torch_version/iter_style.py:80-145`` and ``torch_version/map_style.py:85-149``
(SURVEY.md §1: "four parallel driver scripts, not one framework entry
point"). Here there is ONE ``train()`` with a pluggable input pipeline
(loader style × sampler × data format are config, not scripts) and a
pluggable :class:`~.models.tasks.Task` (classification / masked-LM /
contrastive).

TPU-native loop design vs. the reference hot loop (SURVEY.md §3.4):

* gradient sync: no DDP wrapper — the step is jitted with a replicated state
  sharding and a ``P('data')`` batch sharding; XLA inserts the gradient
  all-reduce (psum) over ICI,
* input prep (normalize/augment/MLM-masking) runs on device fused into the
  step (:mod:`.ops.image`, :mod:`.models.tasks`), not per-row on host,
* no per-step ``loss.item()`` D2H sync (``lance_iterable.py:115``): the loss
  stays on device in a running accumulator and is fetched once per epoch,
* loader-stall is measured explicitly (BASELINE metric) by timing
  ``next(loader)`` against the device step.
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state

from .data.format import Dataset
from .models.tasks import Task, get_task
from .obs.spans import span as obs_span
from .parallel.mesh import (
    batch_sharding,
    get_mesh,
    make_global_batch,
    maybe_initialize_distributed,
    process_topology,
    replicated_sharding,
)
from .utils import compiletrack
from .utils.metrics import MetricLogger, StepTimer

__all__ = [
    "TrainConfig",
    "TrainState",
    "train",
    "create_train_state",
    "create_sharded_train_state",
    "make_optimizer",
    "lr_schedule_fn",
    "make_train_step",
    "make_eval_step",
    "evaluate",
]


class TrainState(train_state.TrainState):
    """TrainState + mutable batch-norm statistics (None for stateless models)."""

    batch_stats: Any = None


@dataclasses.dataclass
class TrainConfig:
    """Flag-for-flag parity with the reference CLI
    (``/root/reference/lance_iterable.py:136-146``) plus TPU/task knobs."""

    dataset_path: str
    val_dataset_path: Optional[str] = None  # held-out split for eval_every /
    # eval_at_end (the reference's Food101 split='test' val loader,
    # torch_version/map_style.py:57); default: eval over the train loader
    val_fraction: float = 0.0  # >0: carve a seeded held-out fraction of the
    # train dataset as the val split (torch random_split equivalent;
    # torch_version/map_style.py:57's train/val separation without a second
    # dataset). Map-style columnar path; composes with --filter (the split
    # happens inside the filtered pool). Mutually exclusive with
    # val_dataset_path.
    task_type: str = "classification"
    num_classes: int = 101
    sampler_type: str = "batch"  # batch | fragment | full (lance_iterable.py:61-69)
    loader_style: str = "iterable"  # iterable | map  (the two reference paths)
    filter: Optional[str] = None  # row predicate ("label < 50"), resolved to
    # an index pool once; map-style columnar path only (see data/filters.py)
    data_format: str = "columnar"  # columnar | folder (the torch_version/ control arm)
    batch_size: int = 512  # GLOBAL batch (reference default, lance_iterable.py:141)
    epochs: int = 10
    max_steps: int = 0  # >0: stop after N train (micro) steps regardless of
    # epochs — compile checks, smoke runs, fixed-step benchmarking. Counted
    # like total_steps/warmup_steps in data steps: under grad_accum an
    # optimizer update lands every grad_accum of these.
    lr: float = 0.05
    momentum: float = 0.9
    # -- optimizer/schedule knobs beyond the reference's fixed-lr SGD
    # (lance_iterable.py:98) --
    optimizer: str = "sgd"  # sgd | adamw
    weight_decay: float = 0.0
    lr_schedule: str = "constant"  # constant | cosine (optional linear warmup)
    warmup_steps: int = 0
    total_steps: Optional[int] = None  # schedule horizon; None = derived from
    # dataset size × epochs at train() time
    grad_clip: float = 0.0  # >0: clip gradients by global norm
    grad_accum: int = 1  # >1: accumulate N micro-steps per optimizer update
    num_workers: int = 0  # >0: decode in N worker processes (get_safe_loader parity)
    shm_workers: bool = True  # worker-pool batches cross the IPC boundary
    # through shared-memory ring slots (data/buffers.py) instead of being
    # pickled — descriptor-only returns, one copy out of the mapped pages.
    # False = legacy pickle transport (the A/B control arm; also the
    # automatic fallback where POSIX shm is unavailable).
    buffer_pool: bool = True  # recycle decode / wire-receive pages through
    # the process BufferPool: decode writes into warm leased pages and the
    # loader returns them after device_put dispatch (bufpool_* metrics on
    # /metrics). False = fault a fresh allocation per batch (pre-r6).
    device_decode: bool = False  # split the JPEG hot loop at the entropy
    # boundary: the host does only the sequential Huffman/entropy decode
    # and ships half-decoded coefficient pages (data/device_decode.py)
    # through the placement ring; dequant + 8x8 IDCT + chroma upsample +
    # YCbCr->RGB + resize run as a pure jitted device kernel
    # (ops/jpeg_device.py, integer-exact, bit-deterministic) applied as a
    # timed transform stage ahead of the train step, where XLA overlaps it
    # with the step like any other device work. Classification only;
    # degrades to the host pixel path (with one warning) when the native
    # coefficient extractor is unavailable. False (--no_device_decode) =
    # the exact r11 host decode path, the A/B control arm.
    token_pack: bool = False  # ragged token plane (text tasks,
    # data/token_pack.py + ops/token_device.py): variable-length sequences
    # ride pool/wire/cache as values+offsets pages with a deterministic
    # FFD pack plan, and one pure jitted kernel scatters them into packed
    # (rows, pack_len) slabs with segment/position ids ahead of the step —
    # the padding the fixed-shape path burns on every short sequence
    # becomes a measured quantity (pad_waste_pct on /metrics) the
    # autotuner can trade against recompile count. masked_lm/causal_lm
    # pack multiple sequences per row (segment-masked attention,
    # per-segment positions); contrastive buckets one caption per slot so
    # row i stays paired with image i. Eval always streams the padded arm
    # (per-sequence metrics need row alignment). False (--no_token_pack) =
    # the exact r14 padded control arm.
    pack_len: int = 0  # packed slot-length cap; 0 = seq_len. A bounded
    # Tunable (with pack_rows_multiple) when the autotuner is on.
    pack_rows_multiple: int = 8  # packed row-count rounding quantum:
    # smaller = less padding waste, more distinct compiled shapes
    data_service_addr: Optional[str] = None  # host:port of a running
    # `ldt serve-data` DataService: decode runs on that host's fleet and this
    # process streams plan-ordered device-ready batches (RemoteLoader) —
    # identical batches to local training on the same seed. Iterable columnar
    # path only; decode knobs (task_type/image_size) must match server-side.
    coordinator_addr: Optional[str] = None  # host:port of a running
    # `ldt coordinator`: like data_service_addr, but the FleetLoader
    # resolves N data servers from the coordinator, stripes this shard's
    # plan across them, and fails over (re-stripe at the resume cursor) on
    # server loss — same bit-identical batch contract, elastic capacity.
    # Mutually exclusive with data_service_addr; NOT the jax multi-host
    # rendezvous (that is coordinator_address, below).
    job_id: Optional[str] = None  # v6 job plane: this run's tenancy on a
    # shared DataService/fleet — per-job resume cursor, fairness weight and
    # admission on the server side. None = the implicit "default" job
    # (downgrade-safe against pre-v6 servers; an explicit id refuses them).
    job_priority: Optional[str] = None  # priority class for job_id
    # ("inference" | "training" | "bulk"); None = server default (training).
    no_ddp: bool = False  # single-device escape hatch (lance_iterable.py:145)
    no_wandb: bool = False  # lance_iterable.py:146
    model_name: Optional[str] = None  # default per task (resnet50 / bert_base / clip)
    pretrained: Optional[str] = None  # path to a torch.save'd torchvision
    # ResNet state_dict: backbone weights + BN stats import into the Flax
    # model (models/pretrained.py); the head stays fresh unless its shape
    # matches — the reference's transfer-learning task shape
    # (modelling/classification.py:6-10). Classification/ResNet only.
    image_size: int = 224
    seq_len: int = 128  # masked_lm / contrastive text length
    vocab_size: Optional[int] = None  # None = the model's own default
    prefetch: int = 2
    producer_threads: int = 4  # decode-producer threads; with the placement
    # plane off (--no_global_batch) these also pipeline the per-batch H2D
    # copy (expensive on tunneled TPU clients) across threads
    global_batch: bool = True  # route every loader through the placement
    # plane (data/placement.py): a dedicated thread slices each host batch
    # per local device, dispatches async H2D, and keeps placement_depth
    # device-resident global batches ahead of the step — next(loader)
    # returns an already-transferred array. False = the pre-r7 control arm:
    # a synchronous make_global_batch closure on the consumer thread
    # (bit-identical batches, H2D counted inside loader stall).
    placement_depth: int = 2  # device-resident batches the placement ring
    # keeps ahead of the step; 2 double-buffers (one consumed, one in
    # flight), more pins extra HBM for little added overlap
    autotune: bool = True  # closed-loop pipeline autotuning (tune/): a
    # background controller snapshots windowed obs/ deltas each interval,
    # attributes the bottleneck, and actuates live knobs — decode worker
    # count, prefetch depth, buffer-pool budget, placement ring depth,
    # fleet stripe width — within their declared bounds. Capacity only:
    # the batch stream stays bit-identical in value and order through any
    # decision. False (--no_autotune) = the exact fixed-knob pipeline of
    # r8 and earlier (no controller thread, no Tunable ever constructed).
    autotune_interval_s: float = 1.0  # controller tick period; decisions
    # additionally sit out a policy cooldown between actuations
    data_echo: int = 1  # >1: run N train steps per host batch ("data
    # echoing", Choi et al. 2019) — each echo re-draws the on-device
    # augmentation / MLM masking rng, so echoes are not exact repeats. When
    # the host pipeline (decode / H2D) is the bottleneck, throughput scales
    # ~N× at a modest statistical cost; when the device is the bottleneck it
    # changes nothing. Composes with device_cache (echo shapes epoch 0; the
    # cache stores each batch once).
    device_cache: bool = False  # HBM-resident dataset: keep epoch-0 batches
    # on device and replay them in later epochs — no host decode, no H2D.
    # Correct for every task here because augmentation / MLM masking run ON
    # DEVICE inside the jitted step (fresh randomness each epoch); the cache
    # holds raw uint8/token batches. Epoch shuffle degrades to batch-order
    # permutation (membership frozen at epoch 0).
    device_cache_gb: float = 8.0  # projected-size guard: fall back to the
    # streaming path (with a warning) when the dataset won't fit
    batch_cache: bool = False  # epoch-coherent decoded-batch cache
    # (data/cache.py): a tiered RAM/disk plane consulted at every local
    # loader's decode boundary — epoch >= 2 (and a restarted run, via the
    # disk tier) streams byte-identical cached batches instead of
    # re-reading fragments and re-running decode. Content-keyed (dataset
    # fingerprint + decode config + plan item), so the stream is
    # bit-identical to the uncached run by construction. Host tier of the
    # same idea device_cache implements in HBM; the two compose (the
    # batch cache feeds the fill epoch). False (--no_batch_cache) = the
    # exact r12 path: no probe, no spill dir, nothing.
    cache_ram_budget_mb: int = 512  # RAM ring budget (BufferPool-leased
    # pages; LRU eviction spills to disk, then releases the leases) — a
    # bounded Tunable the autotuner can actuate
    cache_disk_budget_mb: int = 2048  # local-disk spill budget (atomic,
    # sha256-verified segment files; oldest evicted over budget) — Tunable
    cache_dir: Optional[str] = None  # spill directory; default
    # ~/.cache/<pkg>/batch-cache (stable across restarts on purpose:
    # that is what makes a resumed job's first epoch decode-free)
    compile_cache: bool = True  # persistent XLA compile cache on accelerator
    # backends (a cold remote-TPU ResNet-50 compile is minutes; warm starts
    # are seconds). Never applies on CPU — see maybe_enable_compile_cache.
    compile_cache_dir: Optional[str] = None  # default ~/.cache/<pkg>/jax
    shuffle: bool = False  # iterable path: epoch batch-order reshuffle
    # (beyond the reference — Lance samplers replay the same order every
    # epoch; map-style shuffles regardless, as DistributedSampler does)
    augment: bool = True
    eval_at_end: bool = True  # rank-0 eval over train loader (lance_iterable.py:125-127)
    eval_every: int = 0  # map-style: val every N epochs (lance_map_style.py:109-112)
    seed: int = 0
    run_name: Optional[str] = None
    metrics_port: Optional[int] = None  # same contract as ServeConfig:
    # None = exporter off, 0 = ephemeral (bound port in the progress log),
    # >0 fixed. Process 0 serves /metrics (Prometheus text: trainer_*
    # step/loader histograms, svc_* RemoteLoader counters, lineage_*
    # per-batch latency attribution) and /healthz for the run's lifetime.
    metrics_host: str = "127.0.0.1"  # exporter bind address; non-loopback
    # is an explicit opt-in (unauthenticated endpoint)
    log_every: int = 50
    log_grad_norm: bool = False  # per-step micro-batch global gradient norm
    # in the progress lines (divergence telemetry; a few fused reductions;
    # under grad_accum the optimizer clips the accumulated mean, not this)
    # -- parallelism beyond the reference's DP-only scope (SURVEY.md §2.3) --
    model_parallelism: int = 1  # tensor-parallel degree ('model' mesh axis)
    seq_parallelism: int = 1  # context-parallel degree ('seq' axis, ring attn)
    remat: bool = False  # rematerialize transformer blocks (long-context)
    flash_attention: bool = False  # Pallas fused attention (TPU; dense elsewhere)
    num_experts: int = 0  # >0: switch-MoE transformer blocks (expert parallel)
    moe_every: int = 2  # MoE on every Nth block
    pipeline_parallelism: int = 1  # GPipe stages over a 'pipe' mesh axis
    pp_microbatches: int = 4  # microbatches per pipeline round
    fsdp: bool = False  # ZeRO-3-style: fully shard params + optimizer state
    # over the 'data' axis; XLA inserts the per-layer all-gathers
    zero_opt: int = 0  # ZeRO gradient/optimizer sharding over the 'data'
    # axis, params replicated. 1 (or legacy True): shard the optimizer
    # MOMENTS only — the SPMD partitioner reduce-scatters gradients into
    # each replica's opt-state shard and all-gathers just the updated
    # params, so optimizer memory scales 1/N with the mesh at no per-layer
    # forward/backward gathers. 2: ZeRO-2 — additionally shard the
    # gradient-accumulation buffer (optax.MultiSteps acc_grads, the
    # persistent gradient state under --grad_accum) and constrain the
    # step's gradients to the same layout (parallel/sharding.py
    # grad_partition_specs), so the backward's gradient never materialises
    # fully replicated. Value-preserving re-layouts both — the loss
    # trajectory matches the unsharded run (pinned by a slow parity
    # test). Mutually exclusive with fsdp (which already shards both).
    # -- aux subsystems the reference lacks (SURVEY.md §5) --
    checkpoint_dir: Optional[str] = None  # orbax save/restore root
    checkpoint_every: int = 1  # save every N epochs
    checkpoint_every_steps: int = 0  # >0: ALSO save every N data steps —
    # step-granular, crash-consistent checkpoints carrying the data-plane
    # cursor (loader state_dict + host rng + counters), so a SIGKILLed run
    # restarts mid-epoch at the exact next batch with a bit-identical
    # stream. Counted in absolute data steps across restarts; with
    # data_echo > 1 saves land at host-batch boundaries. Epoch-boundary
    # saves (checkpoint_every) continue independently.
    resume: bool = True  # restore the latest checkpoint if one exists
    profile_dir: Optional[str] = None  # jax.profiler trace of early steps
    # -- multi-host rendezvous (torchrun MASTER_ADDR/RANK/WORLD_SIZE parity) --
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None


def _task_from_config(config: TrainConfig, mesh=None) -> Task:
    attention_fn = None
    if config.flash_attention and (
        config.seq_parallelism > 1 or config.pipeline_parallelism > 1
    ):
        raise ValueError(
            "flash_attention cannot combine with seq_parallelism or "
            "pipeline_parallelism (they select their own attention path)"
        )
    if config.seq_parallelism > 1:
        if config.task_type != "masked_lm":
            raise ValueError(
                "seq_parallelism>1 requires a sequence model (masked_lm)"
            )
        if config.seq_len % config.seq_parallelism:
            raise ValueError(
                f"seq_len {config.seq_len} not divisible by "
                f"seq_parallelism {config.seq_parallelism}"
            )
        from .parallel.ring_attention import make_ring_attention

        attention_fn = make_ring_attention(mesh)
    elif config.pipeline_parallelism > 1:
        if config.task_type != "masked_lm":
            raise ValueError(
                "pipeline_parallelism>1 requires a sequence model (masked_lm)"
            )
    elif config.flash_attention:
        if config.task_type not in ("masked_lm", "causal_lm"):
            raise ValueError("flash_attention requires a sequence model")
        from .ops.flash import make_flash_attention

        # causal_lm binds the kernel's fused autoregressive masking (also
        # skips the fully-masked upper blocks).
        attention_fn = make_flash_attention(
            causal=config.task_type == "causal_lm"
        )
    return get_task(
        config.task_type,
        num_classes=config.num_classes,
        model_name=config.model_name,
        image_size=config.image_size,
        seq_len=config.seq_len,
        vocab_size=config.vocab_size,
        augment=config.augment,
        attention_fn=attention_fn,
        remat=config.remat,
        num_experts=config.num_experts,
        moe_every=config.moe_every,
        pipeline_parallelism=config.pipeline_parallelism,
        pp_microbatches=config.pp_microbatches,
        mesh=mesh,
    )


def lr_schedule_fn(config: TrainConfig, total_steps: Optional[int] = None):
    """The learning-rate schedule from the config knobs: a float (constant)
    or an ``optax`` schedule callable over OPTIMIZER updates (data steps are
    converted under ``grad_accum`` — see :func:`make_optimizer`). Shared by
    the optimizer build and the per-step lr logging."""
    horizon = total_steps or config.total_steps
    accum = max(config.grad_accum, 1)
    if config.lr_schedule == "constant":
        if config.warmup_steps > 0:
            # Linear warmup, then constant — warmup_steps must never be a
            # silent no-op just because no decay schedule was chosen.
            return optax.linear_schedule(
                0.0, config.lr, max(-(-config.warmup_steps // accum), 1)
            )
        return config.lr
    if config.lr_schedule == "cosine":
        if not horizon:
            raise ValueError("cosine schedule needs total_steps")
        horizon = max(-(-horizon // accum), 1)
        warmup = -(-config.warmup_steps // accum)
        if warmup > 0:
            return optax.warmup_cosine_decay_schedule(
                0.0, config.lr, warmup, max(horizon, warmup + 1)
            )
        return optax.cosine_decay_schedule(config.lr, horizon)
    raise ValueError(f"Invalid lr_schedule: {config.lr_schedule}")


def make_optimizer(config: TrainConfig, total_steps: Optional[int] = None):
    """Optax chain from the config knobs.

    The reference trains with a single fixed-lr SGD
    (``/root/reference/lance_iterable.py:98``); that stays the default. Beyond
    it: AdamW (decoupled weight decay), SGD + classic L2 weight decay (the
    decay term rides the momentum buffer, torch ``SGD(weight_decay=)``
    semantics), cosine decay with linear warmup, global-norm gradient
    clipping, and gradient accumulation (``optax.MultiSteps`` — N
    micro-batches per parameter update, the memory-for-batch-size trade that
    needs no loader change).

    ``total_steps`` / ``warmup_steps`` are counted in *data* (micro) steps;
    with ``grad_accum > 1`` they are converted to optimizer updates here,
    since ``MultiSteps`` advances the inner schedule once per accumulation
    window — otherwise the schedule would traverse only 1/N of its horizon.
    """
    lr = lr_schedule_fn(config, total_steps)
    parts = []
    if config.grad_clip > 0:
        parts.append(optax.clip_by_global_norm(config.grad_clip))
    if config.optimizer == "sgd":
        if config.weight_decay > 0:
            parts.append(optax.add_decayed_weights(config.weight_decay))
        parts.append(optax.sgd(lr, momentum=config.momentum))
    elif config.optimizer == "adamw":
        parts.append(optax.adamw(lr, weight_decay=config.weight_decay))
    else:
        raise ValueError(f"Invalid optimizer: {config.optimizer}")
    tx = parts[0] if len(parts) == 1 else optax.chain(*parts)
    if config.grad_accum > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=config.grad_accum)
    return tx


def create_train_state(rng: jax.Array, task: Task, config: TrainConfig,
                       total_steps: Optional[int] = None) -> TrainState:
    variables = task.init_variables(rng)
    tx = make_optimizer(config, total_steps)
    return TrainState.create(
        apply_fn=None,
        params=variables["params"],
        batch_stats=variables.get("batch_stats"),
        tx=tx,
    )


def create_sharded_train_state(
    rng: jax.Array, task: Task, config: TrainConfig, mesh, rules=(),
    *, fsdp_axis: Optional[str] = None, zero_axis: Optional[str] = None,
    zero_level: int = 1, total_steps: Optional[int] = None,
):
    """Initialize the TrainState *directly sharded* over the mesh.

    Init runs under jit with ``out_shardings`` derived from the partition
    rules, so each device materialises only its parameter shard — no host
    round-trip, no full replica anywhere (how a model larger than one chip's
    HBM gets initialized). With ``fsdp_axis``, rule-unmatched leaves (params
    AND their optimizer state) fully shard over that axis instead of
    replicating; with ``zero_axis``, only the optimizer state does (ZeRO-1 —
    each device initializes just its momentum/moment shard). Returns
    ``(state, sharding_pytree)``.
    """
    from .parallel.sharding import state_shardings

    # One tx instance shared by the eval_shape pass and the jitted init —
    # TrainState's static metadata (tx, apply_fn) must be identical in the
    # out_shardings prefix tree and the actual output.
    tx = make_optimizer(config, total_steps)

    def _create(r):
        variables = task.init_variables(r)
        return TrainState.create(
            apply_fn=None,
            params=variables["params"],
            batch_stats=variables.get("batch_stats"),
            tx=tx,
        )

    abstract = jax.eval_shape(_create, rng)
    shardings = state_shardings(abstract, mesh, rules, fsdp_axis=fsdp_axis,
                                zero_axis=zero_axis, zero_level=zero_level)
    return jax.jit(_create, out_shardings=shardings)(rng), shardings


def _variables(state: TrainState) -> dict:
    v = {"params": state.params}
    if state.batch_stats is not None:
        v["batch_stats"] = state.batch_stats
    return v


def make_train_step(task: Task, mesh, *, donate: bool = True,
                    state_sharding=None, batch_spec=None,
                    grad_norm: bool = False, grad_sharding=None):
    """Build the jitted sharded train step.

    Pure DP (the reference's scope): state replicated (``P()``), every batch
    leaf sharded ``P('data')`` on its leading dim; under those in-shardings
    XLA turns the per-shard gradients into a mean via an all-reduce over ICI —
    the compiled equivalent of DDP's bucketed NCCL all-reduce
    (``/root/reference/lance_iterable.py:93-97``; ``README.md:185``).

    Beyond DP: pass ``state_sharding`` (a NamedSharding pytree from
    :func:`~.parallel.sharding.state_shardings`) to tensor-parallel-shard
    params + optimizer state over the ``'model'`` axis, and ``batch_spec``
    (e.g. ``P('data', 'seq')``) to lay token batches out for context
    parallelism. The SPMD partitioner derives every collective from these
    annotations — no communication code here.
    """

    def step(state: TrainState, batch, rng):
        def loss_of(params):
            variables = dict(_variables(state), params=params)
            outputs, new_state = task.forward(variables, batch, True, rng)
            return task.loss(outputs, batch), new_state

        (loss, new_model_state), grads = jax.value_and_grad(
            loss_of, has_aux=True
        )(state.params)
        if grad_sharding is not None:
            # ZeRO-2's in-flight half: pin the gradients to the moment/
            # accumulator layout (grad_partition_specs), so the SPMD
            # partitioner lowers the data-axis gradient mean to
            # reduce-scatter + shard-local optimizer update + param
            # all-gather instead of a full all-reduce per device. A pure
            # re-layout — gradient VALUES are unchanged.
            grads = jax.lax.with_sharding_constraint(grads, grad_sharding)
        state = state.apply_gradients(grads=grads)
        if new_model_state is not None and "batch_stats" in new_model_state:
            state = state.replace(batch_stats=new_model_state["batch_stats"])
        if grad_norm:
            # Global norm of THIS micro-batch's gradient (a few extra sum-
            # reductions XLA fuses into the backward) — divergence telemetry
            # (--log_grad_norm). With grad_accum > 1 the optimizer clips the
            # accumulated MEAN inside MultiSteps (smoother than this), which
            # is not observable from here.
            return state, loss, optax.global_norm(grads)
        return state, loss

    repl = replicated_sharding(mesh)
    state_sh = state_sharding if state_sharding is not None else repl
    if batch_spec is not None:
        from jax.sharding import NamedSharding

        data = NamedSharding(mesh, batch_spec)
    else:
        data = batch_sharding(mesh)
    out_sh = (state_sh, repl, repl) if grad_norm else (state_sh, repl)
    jitted = jax.jit(
        step,
        in_shardings=(state_sh, data, repl),
        out_shardings=out_sh,
        donate_argnums=(0,) if donate else (),
    )
    if compiletrack.enabled():
        # Compile-witness funnel (LDT1703's evidence half): count distinct
        # trace signatures per step def site — steady state must show zero
        # post-warmup compiles, and scripts/ci.sh gates on exactly that.
        jitted = compiletrack.wrap_jit(jitted, step)
    return jitted


def make_eval_step(task: Task, mesh, *, state_sharding=None, batch_spec=None):
    """Returns ``step(state, batch) -> (metric_sum, example_count)``.

    A batch carrying ``_weight`` (the full-coverage eval loader's pad mask,
    ``make_eval_pipeline``) contributes ``(metric·w).sum(), w.sum()`` so
    wrap-around pad rows count zero; otherwise the count is the static batch
    size. Two jitted variants — the weight array is rank-1 regardless of the
    task's batch rank, so it takes its own ``P('data')`` sharding rather
    than the batch-wide spec."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = replicated_sharding(mesh)
    state_sh = state_sharding if state_sharding is not None else repl
    if batch_spec is not None:
        data = NamedSharding(mesh, batch_spec)
    else:
        data = batch_sharding(mesh)
    wsharding = NamedSharding(mesh, P("data"))

    def _metric(state: TrainState, batch):
        outputs, _ = task.forward(_variables(state), batch, False, None)
        return task.metric(outputs, batch)

    def _plain(state: TrainState, batch):
        m = _metric(state, batch)
        return m.sum(), jnp.asarray(m.shape[0], jnp.float32)

    def _weighted(state: TrainState, batch, w):
        m = _metric(state, batch)
        return (m * w).sum(), w.sum()

    plain = jax.jit(_plain, in_shardings=(state_sh, data),
                    out_shardings=repl)
    weighted = jax.jit(_weighted, in_shardings=(state_sh, data, wsharding),
                       out_shardings=repl)
    if compiletrack.enabled():
        plain = compiletrack.wrap_jit(plain, _plain)
        weighted = compiletrack.wrap_jit(weighted, _weighted)

    def step(state: TrainState, batch):
        batch = dict(batch)
        w = batch.pop("_weight", None)
        if w is None:
            return plain(state, batch)
        return weighted(state, batch, w)

    return step


def evaluate(state, loader, eval_step) -> float:
    """Mean per-example metric over a loader — the ``evaluate`` equivalent
    (``/root/reference/modelling/classification.py:20-32``). The per-batch
    (sum, count) pairs accumulate ON DEVICE (async dispatch); the only host
    sync is the final ``float()`` — unlike the reference's per-step
    ``.item()`` (``lance_iterable.py:115``) this never serialises eval on
    D2H. Pad rows from the full-coverage eval loader carry weight 0 in both
    the sum and the count."""
    num = None
    den = None
    batches = 0
    for batch in loader:
        part, count = eval_step(state, batch)
        num = part if num is None else num + part
        den = count if den is None else den + count
        batches += 1
        if batches % 32 == 0:
            # Bound dispatch depth: each in-flight eval step pins its batch
            # on device; one scalar fetch per 32 batches caps that without
            # serialising every step as the reference's .item() did. (Fetch,
            # not block_until_ready — the latter returns early on the
            # tunneled TPU backend.)
            if compiletrack.enabled():
                compiletrack.track_transfer(
                    "d2h", getattr(num, "nbytes", 0) or 0)
            _ = float(num)  # ldt: ignore[LDT1704] -- deliberate dispatch-depth drain: one scalar fetch per 32 eval batches caps in-flight memory
    if den is None:
        return 0.0
    if compiletrack.enabled():
        compiletrack.track_transfer("d2h", getattr(den, "nbytes", 0) or 0)
    total = float(den)  # ldt: ignore[LDT1704] -- the eval-end fetch: the one place the mean leaves the device
    return float(num) / total if total else 0.0  # ldt: ignore[LDT1704] -- same eval-end fetch; num is already drained one line up


def _loader_buffer_pool(config: TrainConfig):
    """The process BufferPool when the knob is on — shared by the decoder
    (lease side) and every pipeline (release side), so pages recycle across
    batches instead of faulting fresh per step."""
    if not config.buffer_pool:
        return None
    from .data.buffers import default_buffer_pool

    return default_buffer_pool()


_TEXT_TASKS = ("masked_lm", "causal_lm", "contrastive")


def _token_pack_config(config: TrainConfig, mesh=None):
    """The run's :class:`~.data.token_pack.TokenPackConfig`, or ``None``
    when the ragged plane is off. ``mesh`` pins ``rows_align`` to the
    data-axis size so every packed grid's row count divides over the
    devices (the autotuner may move ``rows_multiple`` freely; the align
    floor is immune)."""
    if not config.token_pack:
        return None
    from .data.token_pack import TokenPackConfig

    align = 1
    if mesh is not None:
        align = int(mesh.shape.get("data", 1))
    return TokenPackConfig(
        pack_len=config.pack_len or config.seq_len,
        rows_multiple=config.pack_rows_multiple,
        rows_align=align,
    )


def _decoder_for(config: TrainConfig, *, for_eval: bool = False, mesh=None):
    from .data.decode import decoder_for_task

    text = config.task_type in _TEXT_TASKS
    return decoder_for_task(
        config.task_type, config.image_size,
        buffer_pool=_loader_buffer_pool(config),
        device_decode=config.device_decode,
        # Eval always streams the padded arm: per-sequence metrics (and
        # the full-coverage loader's _weight pads) need row alignment the
        # FFD pack gives up.
        token_pack=None if for_eval else _token_pack_config(config, mesh),
        seq_len=config.seq_len if text else None,
    )


def _make_worker_pool(config: TrainConfig, dataset, mesh=None):
    """Persistent decode-worker pool (``num_workers``/``persistent_workers``
    parity, ``/root/reference/lance_map_style.py:60-69``). None when
    ``num_workers == 0`` — decode then runs on the producer thread + the
    native decoder's own thread pool."""
    if config.num_workers <= 0:
        return None
    from .data.workers import WorkerPool, columnar_spec, folder_spec

    decode = _decoder_for(config, mesh=mesh)
    columns = getattr(decode, "required_columns", None)
    transport = "shm" if config.shm_workers else "pickle"
    pool = _loader_buffer_pool(config)
    if config.data_format == "folder":
        from .data.authoring import _folder_samples

        samples, _ = _folder_samples(config.dataset_path)
        return WorkerPool(folder_spec(samples), decode, config.num_workers,
                          transport=transport, buffer_pool=pool)
    return WorkerPool(
        columnar_spec(config.dataset_path), decode, config.num_workers,
        columns=columns, transport=transport, buffer_pool=pool,
    )


def _make_placement(config: TrainConfig, mesh):
    """The run's :class:`~.data.placement.PlacementPlane` — ``None`` when
    the synchronous control arm (``--no_global_batch``) is selected. One
    plane per loader build; the plane shares the process BufferPool with
    the decode side so leases released at transfer dispatch warm the next
    decode."""
    if not config.global_batch or mesh is None:
        return None
    from .data.placement import PlacementPlane

    return PlacementPlane(
        mesh,
        seq_axis="seq" if config.seq_parallelism > 1 else None,
        depth=config.placement_depth,
        buffer_pool=_loader_buffer_pool(config),
    )


def _build_loader(config: TrainConfig, dataset, mesh, epoch: int = 0,
                  workers=None, index_pool=None, batch_cache=None,
                  folder_fp=None):
    process_index, process_count = process_topology()
    per_process = config.batch_size // process_count
    if per_process * process_count != config.batch_size:
        raise ValueError(
            f"global batch {config.batch_size} not divisible by "
            f"{process_count} processes"
        )
    decode = _decoder_for(config, mesh=mesh)
    # Placement: default is the async plane (host batches out of the
    # pipelines, one placement thread owning H2D); the control arm keeps
    # the legacy synchronous closure on the consumer thread.
    plane = _make_placement(config, mesh)
    if plane is not None:
        put = None
    else:
        put = partial(
            make_global_batch,
            mesh=mesh,
            seq_axis="seq" if config.seq_parallelism > 1 else None,
        )

    # Every arm is ONE LoaderGraph assembly (data/graph.py): the source/
    # transport choice is the only thing that varies; decode boundary,
    # cache, buffers, prefetch, and placement compose identically.
    from .data.graph import (
        Buffers,
        Cache,
        Decode,
        DevicePut,
        FleetTransport,
        FolderSource,
        InProcess,
        LanceSource,
        LoaderGraph,
        MapStyleSource,
        Place,
        Pool,
        Prefetch,
        ServiceTransport,
    )

    def _assemble(source, decode_node, *mid):
        nodes = [source, decode_node, *mid,
                 Buffers(_loader_buffer_pool(config)), DevicePut(put)]
        if plane is not None:
            nodes.append(Place(plane))
        graph = LoaderGraph(*nodes)
        graph.compile()
        return graph

    if config.data_service_addr or config.coordinator_addr:
        # Disaggregated input plane: decode runs in remote DataService
        # processes; this process only streams host batches and dispatches
        # device_put. The servers build the identical epoch Plan (same
        # LanceSource.shard_plans), so batches match local training
        # bit-for-bit on the same seed — whether one server
        # (ServiceTransport) or a coordinated fleet striped across N of
        # them (FleetTransport).
        source = LanceSource(
            None,
            config.sampler_type,
            per_process,
            process_index,
            process_count,
            shuffle=config.shuffle,
            seed=config.seed,
            epoch=epoch,
            # Dataset-identity skew check (r13): when this host can read
            # the dataset too, declare its fingerprint so a server backed
            # by a DIFFERENT copy is rejected at connect time.
            dataset_fingerprint=(
                dataset.fingerprint() if dataset is not None else None
            ),
        )
        decode_node = Decode(
            columns=getattr(decode, "required_columns", None),
            task_type=config.task_type,
            image_size=config.image_size,
            # Text-task decode shape, skew-checked like image_size (a
            # seq_len-64 trainer against a seq_len-128 server would crash
            # mid-epoch on the model's max_len).
            seq_len=(
                config.seq_len if config.task_type in _TEXT_TASKS else None
            ),
            device_decode=config.device_decode,
            token_pack=config.token_pack,
        )
        transport = (
            FleetTransport(config.coordinator_addr,
                           job_id=config.job_id,
                           job_priority=config.job_priority)
            if config.coordinator_addr
            else ServiceTransport(config.data_service_addr,
                                  job_id=config.job_id,
                                  job_priority=config.job_priority)
        )
        loader = _assemble(source, decode_node,
                           Prefetch(config.prefetch), transport)
        if len(loader) == 0:
            raise ValueError(
                "empty plan from data service: dataset smaller than one "
                f"global batch ({config.batch_size})"
            )
        return loader
    if config.filter and config.data_format != "columnar":
        raise ValueError("filter= needs the columnar store (data_format="
                         "'columnar'); folder trees have no row predicates")
    prefetch_node = Prefetch(config.prefetch,
                             producers=config.producer_threads)
    if config.data_format == "folder":
        # Control arm: plain files, no columnar store (torch_version/ twin,
        # reference README.md:286-290).
        source = FolderSource(
            config.dataset_path,
            per_process,
            process_index,
            process_count,
            loader_style=config.loader_style,
            # Map-style always reshuffles (DistributedSampler semantics);
            # the iterable arm's batch-order shuffle is opt-in, matching the
            # columnar iterable path.
            shuffle=True if config.loader_style == "map" else config.shuffle,
            seed=config.seed,
            epoch=epoch,
            dataset_fingerprint=folder_fp,
        )
        loader = _assemble(source, Decode(decode), Cache(batch_cache),
                           Pool(workers), prefetch_node, InProcess())
        if len(loader) == 0:
            raise ValueError("folder smaller than one global batch")
        if (
            config.task_type == "classification"
            and loader.num_classes > config.num_classes
        ):
            raise ValueError(
                f"folder has {loader.num_classes} class directories but "
                f"num_classes={config.num_classes}; out-of-range labels "
                "would be silently clamped by the XLA gather"
            )
        return loader
    columns = getattr(decode, "required_columns", None)
    if config.filter and config.loader_style != "map":
        raise ValueError(
            "filter= needs the map-style loader (the predicate resolves to "
            "an index pool; iterable range plans read contiguous rows); pass "
            "loader_style='map'"
        )
    if config.loader_style == "map":
        if config.filter and index_pool is None:
            # Fallback for direct calls / held-out val datasets; train()
            # resolves the TRAIN pool once and passes it down.
            index_pool = dataset.filter_indices(config.filter)
        if index_pool is not None and len(index_pool) < config.batch_size:
            raise ValueError(
                f"filter {config.filter!r} keeps {len(index_pool)} rows — "
                f"fewer than one global batch ({config.batch_size})"
            )
        source = MapStyleSource(
            dataset,
            per_process,
            process_index,
            process_count,
            seed=config.seed,
            epoch=epoch,
            index_pool=index_pool,
        )
    else:
        source = LanceSource(
            dataset,
            config.sampler_type,
            per_process,
            process_index,
            process_count,
            shuffle=config.shuffle,
            seed=config.seed,
            epoch=epoch,
        )
    loader = _assemble(source, Decode(decode, columns=columns),
                       Cache(batch_cache), Pool(workers), prefetch_node,
                       InProcess())
    if len(loader) == 0:
        raise ValueError(
            "empty plan: dataset smaller than one global batch "
            f"({dataset.count_rows()} rows, global batch {config.batch_size})"
        )
    return loader


def _split_val_pool(config: TrainConfig, dataset, index_pool):
    """Held-out validation fraction: a seeded disjoint split of the
    (possibly filtered) row pool. Deterministic across processes — every
    process derives the same split, preserving the equal-step invariant.
    Returns ``(train_pool, val_pool)``, both sorted global row indices."""
    pool = (
        index_pool
        if index_pool is not None
        else np.arange(dataset.count_rows(), dtype=np.int64)
    )
    if len(pool) < 2 * config.batch_size:
        # Both sides need at least one full global batch (also guards an
        # empty --filter pool before any division below).
        raise ValueError(
            f"val_fraction needs at least two global batches "
            f"(2×{config.batch_size}) in the pool; have {len(pool)} rows"
        )
    n_val = int(len(pool) * config.val_fraction)
    if n_val < config.batch_size:
        # Eval needs at least one full global batch; never silently.
        import warnings

        warnings.warn(
            f"val_fraction {config.val_fraction} yields {n_val} rows — "
            f"raised to one global batch ({config.batch_size} rows = "
            f"{config.batch_size / len(pool):.1%} of the pool)",
            stacklevel=3,
        )
        n_val = config.batch_size
    if len(pool) - n_val < config.batch_size:
        raise ValueError(
            f"val_fraction {config.val_fraction} leaves fewer than one "
            f"global batch ({config.batch_size}) on one side of the "
            f"split ({len(pool)} rows available)"
        )
    perm = np.random.default_rng(config.seed).permutation(len(pool))
    return np.sort(pool[perm[n_val:]]), np.sort(pool[perm[:n_val]])


def _build_eval_loader(config: TrainConfig, dataset, mesh, index_pool=None,
                       batch_cache=None, folder_fp=None):
    """Full-coverage eval loader: every row exactly once per eval, the tail
    batch padded by wrap-around rows carried with ``_weight`` 0.0 — single
    compiled batch shape, equal step counts on every process (r3 verdict:
    batch-sampler eval dropped the tail; full_scan's ragged tail recompiled).
    Training's ``loader_style``/``sampler_type`` don't apply here: eval
    coverage is exact by construction on both storage arms."""
    from .data.pipeline import make_eval_pipeline

    process_index, process_count = process_topology()
    decode = _decoder_for(config, for_eval=True)
    plane = _make_placement(config, mesh)
    if plane is not None:
        put = None
    else:
        put = partial(
            make_global_batch,
            mesh=mesh,
            seq_axis="seq" if config.seq_parallelism > 1 else None,
        )
    if config.data_format == "folder":
        from .data.authoring import _folder_samples
        from .data.folder import read_sample_batch

        samples, _ = _folder_samples(config.dataset_path)

        def read_fn(idx):
            return read_sample_batch(samples, idx)

        total = len(samples)
        # The run-scoped fingerprint train() computed once; the direct-
        # call fallback (library users) derives it here, still only when
        # a cache is actually bound.
        dataset_fp = folder_fp
        if dataset_fp is None and batch_cache is not None:
            from .data.cache import folder_fingerprint

            dataset_fp = folder_fingerprint(samples)
    else:
        columns = getattr(decode, "required_columns", None)

        def read_fn(idx):
            return dataset.take(idx, columns=columns)

        total = dataset.count_rows()
        # The fingerprint was computed once at Dataset construction —
        # eval rebuilds this loader every eval_every epochs and must
        # REUSE it, not re-derive it (the r13 satellite).
        dataset_fp = dataset.fingerprint()
        if config.filter and index_pool is None:
            index_pool = dataset.filter_indices(config.filter)
    loader = make_eval_pipeline(
        read_fn,
        total,
        config.batch_size,
        process_index,
        process_count,
        decode,
        put,
        prefetch=config.prefetch,
        producers=config.producer_threads,
        index_pool=index_pool,
        buffer_pool=_loader_buffer_pool(config),
        batch_cache=batch_cache,
        dataset_fingerprint=dataset_fp,
    )
    return plane.wrap(loader) if plane is not None else loader


def maybe_enable_compile_cache(platform: str, cache_dir: Optional[str] = None,
                               *, enabled: bool = True):
    """Persistent XLA compile cache for accelerator backends.

    A cold ResNet-50 train-step compile is minutes on a remote/tunneled TPU;
    the persistent cache makes every later `train()` start warm. NEVER on
    CPU: XLA:CPU's persistent cache stores AOT machine code whose round-trip
    is unsound for shard_map collective programs and across hosts (see
    tests/conftest.py). Returns the cache dir applied, or None.
    """
    if not enabled or platform == "cpu":
        return None
    cache_dir = os.path.expanduser(
        cache_dir
        or os.path.join("~", ".cache", "lance_distributed_training_tpu",
                        "jax")
    )
    try:
        # Threshold first: if either update raises (flag names move across
        # JAX releases), the cache stays fully disabled — the return value
        # must never say None while the cache is half-enabled.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:  # noqa: BLE001 — cache is an optimisation, never fatal
        return None
    return cache_dir


class _CkptJournal:
    """Checkpoint bookkeeping shared between the step loop and ``train()``'s
    ``finally`` (the emergency-save path). Updated only at completed-step
    boundaries, so whatever it holds always pairs a model state with the
    cursor naming the exact next batch — a signal or exception arriving
    mid-step can never save an inconsistent pair."""

    def __init__(self, resume_global_step: int = 0):
        self.state = None  # latest post-step TrainState (a reference)
        self.rng = None  # the key as of the same boundary
        self.cursor_base: Optional[dict] = None  # loader {"epoch","step"}
        self.abs_step = resume_global_step  # absolute completed data steps
        self.saved_step = resume_global_step  # newest persisted abs_step
        self.preempted = False

    @property
    def dirty(self) -> bool:
        return self.state is not None and self.abs_step > self.saved_step

    def make_cursor(self) -> dict:
        from .utils.checkpoint import pack_rng_key

        cursor = dict(self.cursor_base or {})
        cursor["global_step"] = int(self.abs_step)
        if self.rng is not None:
            cursor["rng"] = pack_rng_key(self.rng)
        return cursor


def train(config: TrainConfig) -> dict:
    """The single training entry point. Returns final metrics."""
    if config.val_fraction:
        # Validate the combo BEFORE any dataset I/O so a bad config fails
        # with its own message, not a dataset-open error.
        if not 0.0 < config.val_fraction < 1.0:
            raise ValueError(
                f"val_fraction must be in (0, 1), got {config.val_fraction}"
            )
        if config.val_dataset_path:
            raise ValueError(
                "val_fraction and val_dataset_path are mutually exclusive"
            )
        if config.data_format != "columnar" or config.loader_style != "map":
            raise ValueError(
                "val_fraction needs the map-style columnar path (the split "
                "is an index pool); pass loader_style='map'"
            )
    if config.data_service_addr and config.coordinator_addr:
        raise ValueError(
            "data_service_addr and coordinator_addr are mutually exclusive "
            "(one names a single server, the other a fleet's coordinator)"
        )
    if config.job_id and not (
        config.data_service_addr or config.coordinator_addr
    ):
        raise ValueError(
            "job_id declares tenancy on a shared data service/fleet — it "
            "needs data_service_addr or coordinator_addr (local decode has "
            "no job plane)"
        )
    if config.job_priority and not config.job_id:
        raise ValueError(
            "job_priority needs an explicit job_id (the implicit default "
            "job always runs at the server's default class)"
        )
    if config.fsdp and config.zero_opt:
        raise ValueError(
            "fsdp and zero_opt are mutually exclusive: fsdp (ZeRO-3) "
            "already shards the optimizer state along with the params"
        )
    if int(config.zero_opt) not in (0, 1, 2):
        raise ValueError(
            f"zero_opt must be 0, 1 (shard optimizer state) or 2 (also "
            f"shard gradient accumulation), got {config.zero_opt!r}"
        )
    if config.device_decode and config.task_type != "classification":
        raise ValueError(
            "device_decode splits the JPEG decode loop and currently "
            f"supports task_type='classification' only, got "
            f"{config.task_type!r}"
        )
    if config.token_pack:
        if config.task_type not in _TEXT_TASKS:
            raise ValueError(
                "token_pack packs token columns and needs a text task "
                f"({'/'.join(_TEXT_TASKS)}), got {config.task_type!r}"
            )
        if config.seq_parallelism > 1 or config.pipeline_parallelism > 1:
            raise ValueError(
                "token_pack is incompatible with seq_parallelism/"
                "pipeline_parallelism: packed batches re-enter the data "
                "layout inside the pack transform and carry no static "
                "sequence split"
            )
        if (config.num_processes or 1) > 1:
            raise ValueError(
                "token_pack currently supports single-process training "
                "only: each process's packed row count is data-dependent, "
                "and multi-host global-batch assembly needs identical "
                "per-process shapes"
            )
        if config.data_service_addr or config.coordinator_addr:
            if (jax.local_device_count() if config.no_ddp is False else 1) > 1:
                raise ValueError(
                    "token_pack over a data service cannot yet align "
                    "packed row counts to a multi-device mesh (the "
                    "server's planner does not know this trainer's device "
                    "count) — run single-device (--no_ddp) or decode "
                    "locally until pack alignment rides the HELLO"
                )
    if (
        config.device_decode
        and (config.num_processes or 1) > 1
        and not (config.data_service_addr or config.coordinator_addr)
    ):
        import warnings

        # Known limit: each host's CoeffImageDecoder grows its canonical
        # page grid independently (to ITS shard's largest image), and
        # global-batch assembly needs identical non-batch dims on every
        # process — shards with different max image sizes would crash
        # mid-epoch. Uniform-size corpora are fine; mixed-size multi-host
        # local decode is not yet.
        warnings.warn(
            "device_decode with multi-process LOCAL decode requires every "
            "process's shard to share the same maximum image size (the "
            "canonical coefficient grid must agree across hosts for "
            "global-batch assembly); mixed-size corpora should stream "
            "pixels (--no_device_decode) or move decode behind one data "
            "service until per-dataset grid pinning lands",
            stacklevel=2,
        )
    if config.placement_depth < 1:
        raise ValueError(
            f"placement_depth must be >= 1, got {config.placement_depth}"
        )
    if config.data_service_addr or config.coordinator_addr:
        remote_knob = (
            "data_service_addr" if config.data_service_addr
            else "coordinator_addr"
        )
        if config.data_format != "columnar" or config.loader_style != "iterable":
            raise ValueError(
                f"{remote_knob} needs the iterable columnar path (the "
                "service streams sampler-plan ranges); pass "
                "loader_style='iterable', data_format='columnar'"
            )
        if config.filter or config.val_fraction:
            raise ValueError(
                "filter/val_fraction resolve index pools locally and cannot "
                f"combine with {remote_knob}"
            )
        if config.num_workers > 0:
            import warnings

            warnings.warn(
                "num_workers>0 has no effect with data_service_addr: decode "
                "runs in the remote DataService (size ITS pool with "
                "`ldt serve-data --num_workers N`)",
                stacklevel=2,
            )
    maybe_initialize_distributed(
        config.coordinator_address, config.num_processes, config.process_id
    )
    devices = jax.devices()
    if config.no_ddp:
        devices = devices[:1]
    maybe_enable_compile_cache(devices[0].platform, config.compile_cache_dir,
                               enabled=config.compile_cache)
    mesh = get_mesh(
        devices,
        model_parallelism=config.model_parallelism,
        seq_parallelism=config.seq_parallelism,
        pipe_parallelism=config.pipeline_parallelism,
    )

    if config.data_format != "columnar":
        dataset = None
    elif config.data_service_addr or config.coordinator_addr:
        # Disaggregated runs: the TPU host may not mount the dataset path at
        # all — train-side reads happen on the service host. Open locally
        # only if present (it unlocks eval + schedule-horizon derivation).
        try:
            dataset = Dataset(config.dataset_path)
        except FileNotFoundError:
            dataset = None
    else:
        dataset = Dataset(config.dataset_path)
    if (
        dataset is None
        and (config.data_service_addr or config.coordinator_addr)
        and (config.eval_at_end or config.eval_every)
        and not config.val_dataset_path
    ):
        raise ValueError(
            "eval needs the dataset readable on this host (eval reads rows "
            f"directly, not through the data service): {config.dataset_path} "
            "is absent — mount it, pass val_dataset_path, or disable eval "
            "(eval_at_end=False, eval_every=0)"
        )
    val_dataset = (
        Dataset(config.val_dataset_path)
        if config.val_dataset_path and config.data_format == "columnar"
        else None
    )
    task = _task_from_config(config, mesh)

    rng = jax.random.key(config.seed)
    rng, init_rng = jax.random.split(rng)
    from .parallel.sharding import batch_partition_spec, rules_for_task

    rules = (
        rules_for_task(task.name, config.model_name)
        if (config.model_parallelism > 1 or config.pipeline_parallelism > 1)
        else ()
    )
    # Row-filter pool: resolved ONCE here (deterministic; per-epoch
    # re-resolution would rescan every fragment at each epoch/eval boundary)
    # and passed down to every train-side loader build.
    index_pool = None
    if (
        config.filter
        and config.data_format == "columnar"
        and config.loader_style == "map"
    ):
        index_pool = dataset.filter_indices(config.filter)
    val_pool = None
    if config.val_fraction > 0:
        index_pool, val_pool = _split_val_pool(config, dataset, index_pool)
    total_steps = config.total_steps
    if total_steps is None and config.lr_schedule != "constant":
        # Schedule horizon: steps/epoch × epochs. rows // batch matches the
        # balanced samplers' drop-last behaviour closely enough for a decay
        # horizon (fragment padding can add a few steps). A --filter pool
        # shrinks the horizon with it.
        if index_pool is not None:
            rows = len(index_pool)
        elif dataset is not None:
            rows = dataset.count_rows()
        elif config.data_service_addr or config.coordinator_addr:
            raise ValueError(
                "lr_schedule needs a horizon, and the dataset is not "
                "readable on this host to derive one — pass total_steps "
                "explicitly with data_service_addr"
            )
        else:
            from .data.authoring import _folder_samples

            rows = len(_folder_samples(config.dataset_path)[0])
        total_steps = (
            max(rows // config.batch_size, 1)
            * config.epochs
            * max(config.data_echo, 1)  # echoes are real optimizer steps
        )
    state, state_sharding = create_sharded_train_state(
        init_rng, task, config, mesh, rules,
        fsdp_axis="data" if config.fsdp else None,
        zero_axis="data" if config.zero_opt else None,
        zero_level=int(config.zero_opt) or 1,
        total_steps=total_steps,
    )
    if config.pretrained:
        # Transfer learning (the reference's actual training task): replace
        # the randomly initialised backbone with the checkpoint's weights,
        # re-committed at the state's own shardings.
        if config.task_type != "classification":
            raise ValueError(
                "--pretrained imports torchvision ResNet checkpoints; task "
                f"{config.task_type!r} has no importer"
            )
        from .models.pretrained import (
            load_torch_state_dict,
            torchvision_resnet_to_flax,
        )

        imported = torchvision_resnet_to_flax(
            load_torch_state_dict(config.pretrained),
            {"params": state.params, "batch_stats": state.batch_stats},
            config.model_name or "resnet50",
        )
        state = state.replace(
            params=jax.device_put(imported["params"], state_sharding.params),
            batch_stats=jax.device_put(
                imported["batch_stats"], state_sharding.batch_stats
            ),
        )
    batch_spec = (
        batch_partition_spec(2, seq_axis="seq")
        if config.seq_parallelism > 1
        else None
    )

    grad_sharding = None
    if int(config.zero_opt) >= 2:
        from jax.sharding import NamedSharding

        from .parallel.sharding import grad_partition_specs

        grad_sharding = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            grad_partition_specs(state.params, mesh),
        )
    train_step = make_train_step(
        task, mesh, state_sharding=state_sharding, batch_spec=batch_spec,
        grad_norm=config.log_grad_norm, grad_sharding=grad_sharding,
    )
    eval_step = make_eval_step(
        task, mesh, state_sharding=state_sharding, batch_spec=batch_spec
    )

    n_devices = len(mesh.devices.flatten())
    logger = MetricLogger(
        run_name=config.run_name
        or f"DP-{config.loader_style}-{config.sampler_type}-"
           f"{config.model_name or task.name}",
        config=dataclasses.asdict(config),
        enabled=not config.no_wandb,
    )
    timer = StepTimer()
    results: dict = {}
    total_start = time.perf_counter()
    global_step = 0

    # Checkpoint/resume — preemption recovery the reference delegates to its
    # launcher with nothing to restore (SURVEY.md §5). Checkpoints are
    # step-granular and crash-consistent (utils/checkpoint.py): the newest
    # INTACT step restores model + optimizer state together with the
    # data-plane cursor (epoch, batches consumed, absolute step, host rng),
    # so the resumed stream — and with it the loss trajectory — is
    # bit-identical to the uninterrupted run. Corrupt/partial checkpoints
    # (the previous preemption's torn write) fall back to the step before.
    ckpt = None
    start_epoch = 0
    resume_epoch_step = 0  # batches already consumed within start_epoch
    resume_global_step = 0  # absolute data steps completed before this run
    if config.checkpoint_dir:
        from .utils.checkpoint import CheckpointManager, unpack_rng_key

        ckpt = CheckpointManager(config.checkpoint_dir)
        if config.resume:
            restored = ckpt.restore_latest(state)
            if restored is not None:
                state, cursor, ck_step = restored
                if cursor is not None:
                    start_epoch = min(
                        int(cursor.get("epoch", 0)), config.epochs
                    )
                    resume_epoch_step = (
                        int(cursor.get("step", 0))
                        if start_epoch < config.epochs else 0
                    )
                    resume_global_step = int(
                        cursor.get("global_step", ck_step)
                    )
                    packed = cursor.get("rng")
                    if packed is not None:
                        # Exact key restore: the split sequence (and the
                        # on-device augment/masking draws) continues bit-
                        # identically to the uninterrupted run.
                        rng = unpack_rng_key(packed)
                    else:
                        rng = jax.random.fold_in(rng, start_epoch)
                else:
                    # Legacy cursorless checkpoint: the step index is
                    # "epochs completed"; resume at the epoch boundary with
                    # the historical fold-in rng (stream position is intact,
                    # only the masking/augment draw order differs).
                    start_epoch = min(ck_step, config.epochs)
                    resume_global_step = int(state.step)  # ldt: ignore[LDT1704] -- one-off resume-cursor read at startup, before the step loop exists
                    rng = jax.random.fold_in(rng, start_epoch)

    # Preemption handling: SIGTERM (k8s eviction, TPU maintenance) sets a
    # flag the step loop polls — the in-flight step finishes, an emergency
    # checkpoint is awaited, the placement ring drains, and train() returns
    # normally (exit 0). The deterministic chaos harness (utils/chaos.py,
    # LDT_CHAOS env) drives the same paths at an exact step for tests/CI.
    from .utils.chaos import StepTrace, TrainerChaos
    from .utils.signals import PreemptionHandler

    # Parse chaos/trace BEFORE installing the handler: a malformed
    # LDT_CHAOS spec raises by design, and must not leak a hijacked
    # SIGTERM disposition behind it.
    chaos = TrainerChaos.from_env()
    trace = StepTrace.from_env()
    preempt = PreemptionHandler().install()
    if chaos is not None:
        chaos.drain_cb = preempt.request
    journal = _CkptJournal(resume_global_step)

    profiling = False

    # Telemetry scrape surface (--metrics_port): process 0 serves the
    # process-wide registry — StepTimer's trainer_* histograms, any
    # RemoteLoader's svc_*/lineage_* series, pipeline_* batch ages — plus a
    # /healthz liveness body, for the lifetime of the run.
    exporter = None
    slo_tracker = None  # SLO burn-down gauges, started with the exporter
    worker_pool = None
    batch_cache = None
    folder_fp = None  # folder-corpus fingerprint, computed once per run
    tuner = None
    run_exc: Optional[BaseException] = None
    try:
        # Everything that can fail lives inside the try — a bind failure on
        # the exporter port, the metrics_port log write, or a pool-spawn
        # error must all still run the finally (logger/ckpt close, and the
        # exporter's bound port once started).
        if config.metrics_port is not None and jax.process_index() == 0:
            from .obs.http import MetricsHTTPServer
            from .obs.registry import default_registry

            from .obs.slo import SLOTracker

            def _lineage_p99(name: str):
                def probe() -> float:
                    hist = default_registry().get(name)
                    if hist is None:
                        return float("nan")  # no traffic yet: skipped
                    return hist.percentile(99)
                return probe

            slo_tracker = SLOTracker(
                probes={
                    "batch_age_p99_ms": _lineage_p99("lineage_batch_age_ms"),
                    "queue_wait_p99_ms": _lineage_p99(
                        "lineage_queue_wait_ms"
                    ),
                },
            ).start()
            exporter = MetricsHTTPServer(
                default_registry(),
                port=config.metrics_port,  # 0 = ephemeral, as serve-data
                host=config.metrics_host,
                healthz_fn=lambda: {"role": "trainer",
                                    "run_name": config.run_name,
                                    "steps": timer.steps,
                                    "slo": slo_tracker.status()},
            ).start()
            logger.log({"metrics_port": exporter.port}, to_wandb=False)
        if not (config.data_service_addr or config.coordinator_addr):
            worker_pool = _make_worker_pool(config, dataset, mesh)
            if config.batch_cache:
                # Epoch-coherent batch cache (--batch_cache): ONE tiered
                # RAM/disk cache for the whole run — the epoch loop
                # rebuilds loaders, the cache outlives them, which is the
                # entire point (epoch >= 2 hits what epoch 1 filled).
                # Remote arms skip it: the cache lives server-side there
                # (ServeConfig.batch_cache), where the decode boundary is.
                from .data.cache import BatchCache

                batch_cache = BatchCache(
                    cache_dir=config.cache_dir,
                    ram_budget_mb=config.cache_ram_budget_mb,
                    disk_budget_mb=config.cache_disk_budget_mb,
                    buffer_pool=_loader_buffer_pool(config),
                )
                if config.data_format == "folder":
                    # Folder-corpus identity, ONCE per run: the loaders
                    # (train, rebuilt per epoch) and every eval-loader
                    # rebuild reuse this instead of re-walking + re-
                    # hashing the tree — on a million-file corpus that
                    # stat+sha sweep per epoch is the churn the r13
                    # satellite exists to prevent.
                    from .data.authoring import _folder_samples
                    from .data.cache import folder_fingerprint

                    folder_fp = folder_fingerprint(
                        _folder_samples(config.dataset_path)[0]
                    )
        if config.autotune:
            # Closed-loop pipeline autotuning (tune/): one controller for
            # the whole run; the epoch loop re-registers each rebuilt
            # loader's knobs. Reads the process registry the exporter
            # already serves, so autotune_* series ride /metrics for free.
            from .tune import AutoTuner

            tuner = AutoTuner(
                interval_s=config.autotune_interval_s,
            ).start()
        return _train_loop(
            config, dataset, val_dataset, mesh, state, rng, train_step,
            eval_step, logger, timer, worker_pool, ckpt, start_epoch,
            total_start, n_devices, results, global_step, profiling,
            index_pool, lr_schedule_fn(config, total_steps), val_pool,
            resume_epoch_step=resume_epoch_step,
            resume_global_step=resume_global_step,
            preempt=preempt, chaos=chaos, trace=trace, journal=journal,
            tuner=tuner, batch_cache=batch_cache, folder_fp=folder_fp,
        )
    except BaseException as exc:
        run_exc = exc
        raise
    finally:
        if config.profile_dir:
            try:  # stop a trace left open by a mid-window exception
                jax.profiler.stop_trace()
            except Exception:
                pass
        if tuner is not None:
            # Before the worker pool: a controller mid-tick must not
            # actuate a resize against a pool that is shutting down.
            tuner.stop()
        if slo_tracker is not None:
            slo_tracker.stop()
        if exporter is not None:
            exporter.stop()
        if worker_pool is not None:
            worker_pool.shutdown()
        if batch_cache is not None:
            # After the loaders are down (the loop exited; producers
            # drained): releases the RAM ring's BufferPool leases. The
            # disk tier stays — it is what makes a restarted run warm.
            batch_cache.close()
        try:
            if ckpt is not None:
                # The crash-path save gap (r8): a preempted OR crashed run
                # must persist its last completed step — AWAITED — before
                # the process exits; ckpt.close() additionally waits out
                # any periodic save still committing in the background.
                try:
                    if journal.dirty and (journal.preempted
                                          or run_exc is not None):
                        if ckpt.save(journal.abs_step, journal.state,
                                     cursor=journal.make_cursor(),
                                     wait=True):
                            journal.saved_step = journal.abs_step
                finally:
                    ckpt.close()
        except Exception:
            # A failed emergency save must fail a SIGTERM drain loudly
            # (never exit 0 claiming a checkpoint it didn't take) — but on
            # the crash path it must not mask the original run exception.
            if run_exc is None:
                raise
        finally:
            # Teardown that must survive a failed save: the process-wide
            # SIGTERM disposition, the trace file, and the metric sinks.
            preempt.uninstall()
            if trace is not None:
                trace.close()
            logger.close()


def _train_loop(config, dataset, val_dataset, mesh, state, rng, train_step,
                eval_step, logger, timer, worker_pool, ckpt, start_epoch,
                total_start, n_devices, results, global_step, profiling,
                index_pool=None, lr_fn=None, val_pool=None, *,
                resume_epoch_step=0, resume_global_step=0, preempt=None,
                chaos=None, trace=None, journal=None, tuner=None,
                batch_cache=None, folder_fp=None):
    if journal is None:
        journal = _CkptJournal(resume_global_step)
    # Device-decode transform stage (--device_decode): one jitted kernel
    # call replacing a batch's coefficient pages with the decoded image —
    # device work dispatched from the consumer thread, so it overlaps the
    # previous step's compute exactly like the H2D ring does. Timed into
    # trainer_transform_ms (dispatch time; the device cost itself lands
    # inside the step's execution window on async backends). Pixel batches
    # (the --no_device_decode arm or the degraded PIL path) pass through,
    # so one handle covers both arms. Applied BEFORE the device_cache
    # fill: the cache then holds finished image batches, decoding each
    # coefficient page exactly once per run.
    transform = None
    transform_hist = None
    device_ms_hist = None
    probe_key = "image"  # leaf the sampled transform-await fetches from
    if config.token_pack:
        # Ragged token plane: the pack kernel (ops/token_device.py)
        # scatters values/offsets pages into packed (rows, L) slabs with
        # segment/position ids — the text-path twin of the device-decode
        # stage below (mutually exclusive by task type). Padded batches
        # (the control arm, and every eval loader) pass through whole.
        from .obs.registry import default_registry
        from .ops.token_device import make_pack_transform

        # Packed grids come out of the replicated-input kernel replicated;
        # re-lay them onto the data axis so the step's in_shardings accept
        # them (the planner's rows_align makes the row count divide).
        transform = make_pack_transform(
            batch_sharding=batch_sharding(mesh) if mesh is not None else None
        )
        transform_hist = default_registry().histogram("trainer_transform_ms")
        device_ms_hist = default_registry().histogram("pack_device_ms")
        probe_key = "input_ids"
    if config.device_decode:
        from .obs.registry import default_registry
        from .ops.jpeg_device import make_batch_transform

        transform = make_batch_transform(config.image_size)
        transform_hist = default_registry().histogram("trainer_transform_ms")
        # decode_device_ms: the kernel's REAL device cost, sampled — every
        # 16th batch the transform is awaited to completion and timed (one
        # sync per 16 steps; the other 15 stay fully async). This is what
        # feeds the autotuner's decode_split attribution and the /metrics
        # series the CI smoke scrapes.
        device_ms_hist = default_registry().histogram("decode_device_ms")
        _eval_raw = eval_step

        def eval_step(state, batch, _inner=_eval_raw, _tx=transform):
            # Eval loaders share the decoder, so their batches carry
            # coefficient pages too (plus _weight, which passes through).
            return _inner(state, _tx(batch))
    # HBM replay tier (--device_cache): epoch-``start`` batches kept on
    # device, replayed afterwards — the fill/replay/size-guard/partial-
    # epoch-exclusion rules now live in the cache plane
    # (data/cache.DeviceReplayCache) next to the host tiers', not as a
    # bespoke list here. See TrainConfig.device_cache.
    from .data.cache import DeviceReplayCache

    dev_cache = DeviceReplayCache(
        enabled=config.device_cache,
        budget_gb=config.device_cache_gb,
        seed=config.seed,
    )
    history: list = []  # per-epoch metrics, returned as results["history"]
    # Schedule position survives resume inside the restored optimizer state;
    # the lr telemetry must count from there, not from this run's step 0.
    base_step = int(state.step)  # ldt: ignore[LDT1704] -- one-off schedule-position read before the loop starts
    trace_done = False  # one profiler window per run
    # Eval-loader selection, shared by eval_every and eval_at_end.
    # Pool precedence: val_fraction split → train pool (eval over the train
    # loader) → a val dataset resolves its OWN filter pool via the fallback
    # in _build_eval_loader. (Eval decodes on producer threads, never the
    # train worker pool — pools are bound to the TRAIN dataset URI.)
    eval_dataset = val_dataset if val_dataset is not None else dataset
    eval_pool = (
        val_pool if val_pool is not None
        else index_pool if val_dataset is None
        else None
    )
    stop = False  # set by max_steps; ends the epoch loop after bookkeeping
    for epoch in range(start_epoch, config.epochs):
        # Mid-epoch resume cursor: batches of THIS epoch already consumed
        # by the checkpointed run (first epoch after a restart only).
        resume_step = resume_epoch_step if epoch == start_epoch else 0
        replay_it = dev_cache.replay_iter(
            epoch, start_epoch,
            shuffled=config.shuffle or config.loader_style == "map",
        )
        replay = replay_it is not None
        if replay:
            it = replay_it
            loader = None
        else:
            loader = _build_loader(config, dataset, mesh, epoch, worker_pool,
                                   index_pool=index_pool,
                                   batch_cache=batch_cache,
                                   folder_fp=folder_fp)
            if resume_step:
                # Position the loader at the cursor: the rebuilt plan is
                # deterministic, so the tail it serves is bit-identical to
                # what the uninterrupted run would have consumed.
                loader.load_state_dict({"epoch": epoch, "step": resume_step})
            it = iter(loader)
        # RemoteLoader exposes ServiceCounters: merge its stall/queue window
        # into per-step progress lines so loader-stall% stays attributable
        # (client receive stall vs server queue vs H2D vs device); a
        # PlacedLoader additionally exposes the placement plane's counters
        # (placement_h2d_s → the h2d_pct progress field). None detaches.
        timer.attach_counters(
            getattr(loader, "counters", None) if loader is not None else None,
            getattr(loader, "placement_counters", None)
            if loader is not None else None,
        )
        if tuner is not None:
            # Register this epoch's live knobs (the loader is rebuilt per
            # epoch; the controller outlives it). Replay epochs
            # (device_cache) have no pipeline to tune — empty the set so a
            # stale epoch's knobs are never actuated.
            from .tune import collect_tunables

            tuner.set_tunables(collect_tunables(
                loader, worker_pool, _loader_buffer_pool(config),
                batch_cache,
            ) if loader is not None else [])
        # Partial-epoch exclusion (PR 7) lives in the cache plane now: a
        # resumed epoch never seeds the replay set.
        filling = dev_cache.start_fill(replay, resume_step)
        timer.reset()
        epoch_start = time.perf_counter()
        loss_sum = jnp.zeros((), jnp.float32)  # stays on device all epoch
        epoch_step = 0
        epoch_batches = resume_step  # host batches consumed this epoch
        while True:
            timer.loader_start()
            with obs_span("train.loader", step=global_step):
                batch = next(it, None)
            timer.loader_stop()
            if batch is None:
                break
            if transform is not None:
                # Coefficient pages → image, on device (dispatch-timed;
                # async backends execute it inside the step window).
                sample = epoch_batches % 16 == 0
                raw = batch
                t0 = time.monotonic_ns()
                with obs_span("train.transform", step=global_step):
                    batch = transform(raw)
                    decoded = batch is not raw
                    if sample and decoded and probe_key in batch:
                        # Await the sampled kernel run so the device-cost
                        # histogram records execution, not dispatch — via a
                        # scalar VALUE fetch, not block_until_ready (the
                        # tunneled TPU backend returns from
                        # block_until_ready before execution completes;
                        # fetching any element forces the producing kernel
                        # to finish). Degraded/padded batches pass through
                        # `raw` unchanged and are never sampled.
                        leaf = batch[probe_key]
                        _ = int(leaf[(0,) * leaf.ndim])
                dt_ms = (time.monotonic_ns() - t0) / 1e6
                transform_hist.observe(dt_ms)
                if sample and decoded:
                    if global_step > 0:
                        # Skip the run's first sample: it pays the kernel's
                        # XLA compile, which would dominate the histogram's
                        # p50 and skew the autotuner's decode_split toward
                        # device_transform_bound on cold starts.
                        device_ms_hist.observe(dt_ms)
            epoch_batches += 1
            if filling:
                refused = dev_cache.admit(batch, len(loader))
                if refused is not None:
                    # First-batch projection over budget: the cache plane
                    # disabled itself; report why, keep streaming.
                    filling = False
                    logger.log(
                        {
                            "device_cache": "disabled",
                            "projected_per_device_gb": round(
                                refused["projected"] / 1e9, 3
                            ),
                            "limit_per_device_gb": round(
                                refused["budget"] / 1e9, 3
                            ),
                        },
                        to_wandb=False,
                    )
            if (
                config.profile_dir
                and epoch == start_epoch
                and jax.process_index() == 0
            ):
                # Trace a post-compile window of the first epoch: from the
                # first host batch at epoch_step >= 2 until epoch_step >= 12
                # (or epoch end). Step 0/1 are compile+warmup noise.
                # Threshold comparisons + a one-shot flag, not equality or a
                # half-open range: with data_echo > 1 epoch_step advances by
                # the echo factor per host batch and can step over any
                # single value — or the whole [2, 12) window when echo >= 12.
                if epoch_step >= 2 and not profiling and not trace_done:
                    jax.profiler.start_trace(config.profile_dir)
                    profiling = True
                elif profiling and epoch_step >= 12:
                    jax.profiler.stop_trace()
                    profiling = False
                    trace_done = True
            for _echo in range(max(config.data_echo, 1)):
                # Data echoing: each echo re-splits the rng, so on-device
                # augmentation / MLM masking differ between echoes of the
                # same host batch (TrainConfig.data_echo).
                rng, step_rng = jax.random.split(rng)
                timer.step_start()
                with obs_span("train.step", step=global_step):
                    if config.log_grad_norm:
                        state, loss, gnorm = train_step(state, batch, step_rng)
                    else:
                        state, loss = train_step(state, batch, step_rng)
                        gnorm = None
                loss_sum = loss_sum + loss
                # Bound the async dispatch queue (each in-flight step pins
                # its global batch on device) — independent of logging, so
                # neither log_every=0 nor a huge log_every can unbound
                # device memory. A scalar VALUE fetch, not
                # block_until_ready: on the tunneled TPU backend
                # block_until_ready returns before execution completes
                # (verified empirically), so only a D2H fetch actually
                # drains the queue — and it doubles as honest timing. Also
                # fetch at log points (log_every may exceed or not divide
                # sync_every), so the drain lands INSIDE the timed step
                # segment and the progress window's rate stays honest.
                sync_every = min(config.log_every or 50, 50)
                if (global_step + 1) % sync_every == 0 or (
                    config.log_every
                    and (global_step + 1) % config.log_every == 0
                ):
                    _ = float(loss)  # ldt: ignore[LDT1704] -- deliberate bounded drain: fetch at sync_every/log points keeps dispatch depth finite
                timer.step_stop()
                global_step += 1
                epoch_step += 1
                if trace is not None:
                    # Resume-fidelity instrument (LDT_STEP_TRACE_PATH):
                    # absolute step + batch hash + loss, compared step-for-
                    # step against a control arm by the chaos harness.
                    trace.record(resume_global_step + global_step, epoch,
                                 batch, loss)
                if 0 < config.max_steps <= global_step:
                    stop = True
                if config.log_every and global_step % config.log_every == 0:
                    # Per-step progress — the reference's live tqdm it/s +
                    # loss (lance_iterable.py:106,116-117). Console/JSONL
                    # only; wandb stays on the per-epoch axis. The loss D2H
                    # is cheap: the fetch above already materialised it.
                    # The wall-clock rate (not the dispatch-time upper
                    # bound) leads the progress line, so it agrees with the
                    # epoch metrics' wall-clock rate on async backends.
                    w = timer.window(batch_size=config.batch_size)
                    wt = w["loader_s"] + w["step_s"]
                    entry = {
                        "step": global_step,
                        "epoch": epoch,
                        "loss": round(float(loss), 4),  # ldt: ignore[LDT1704] -- log-interval telemetry fetch of the already-drained scalar
                        "images_per_sec": w["images_per_sec_wall"],
                        "images_per_sec_dispatch":
                            w["images_per_sec_dispatch"],
                        "loader_stall_pct": (
                            100.0 * w["loader_s"] / wt if wt else 0.0
                        ),
                    }
                    if "placement_h2d_s" in w:
                        # H2D dispatch time this window (runs on the
                        # placement thread, overlapping the step) as a
                        # share of the same loader+step denominator — the
                        # transfer cost the pre-r7 accounting folded
                        # invisibly into loader_stall_pct.
                        entry["h2d_pct"] = (
                            100.0 * w["placement_h2d_s"] / wt if wt else 0.0
                        )
                    # Data-service windows (RemoteLoader counters attached
                    # to the timer): svc_client_stall_s, svc_reconnects, …
                    entry.update({
                        k: round(v, 4) if isinstance(v, float) else v
                        for k, v in w.items() if k.startswith("svc_")
                    })
                    if lr_fn is not None:
                        # Schedules count optimizer updates, not
                        # micro-steps; base_step carries the restored
                        # position across resume.
                        updates = (base_step + global_step) // max(
                            config.grad_accum, 1
                        )
                        entry["lr"] = float(
                            lr_fn(updates) if callable(lr_fn) else lr_fn
                        )
                    if gnorm is not None:
                        entry["grad_norm"] = round(float(gnorm), 4)  # ldt: ignore[LDT1704] -- log-interval divergence telemetry, rides the loss drain
                    if config.data_echo > 1:
                        # The windowed rate counts echoed steps; report the
                        # unique-data rate next to it (as the epoch metrics
                        # do) so the live stream is never silently inflated.
                        entry["data_echo"] = config.data_echo
                        entry["unique_images_per_sec"] = (
                            entry["images_per_sec"] / config.data_echo
                        )
                    logger.log(entry, to_wandb=False)
                if stop:
                    break
            # Step boundary: the journal always pairs the post-step model
            # state with the cursor naming the NEXT batch (the loader's
            # state_dict reads "batches handed out", which at this point
            # equals batches consumed — see the data/pipeline.py contract).
            journal.state = state
            journal.rng = rng
            journal.abs_step = resume_global_step + global_step
            if loader is not None and hasattr(loader, "state_dict"):
                cursor_base = dict(loader.state_dict())
                cursor_base.setdefault("epoch", epoch)
            else:
                # device_cache replay arm: the cached stream is the FROZEN
                # epoch-0 batch set under a cache-local permutation — for
                # shuffled/map configs a cacheless restart building the
                # fresh epoch-e plan would serve a DIFFERENT set/order, so
                # a mid-epoch cursor here would silently skip and repeat
                # samples. Pin the epoch start instead: a restart re-runs
                # this epoch from storage — deterministic over-training of
                # up to one epoch, never silently lost data.
                cursor_base = {"epoch": epoch, "step": 0}
            journal.cursor_base = cursor_base
            if (
                ckpt is not None
                and config.checkpoint_every_steps > 0
                and journal.abs_step
                >= journal.saved_step + config.checkpoint_every_steps
            ):
                # Async step checkpoint (the epoch-boundary save awaits via
                # ckpt.close()); ">= saved + N" rather than "% N" so
                # data_echo's multi-step jumps can't skip the trigger.
                if ckpt.save(journal.abs_step, state,
                             cursor=journal.make_cursor()):
                    journal.saved_step = journal.abs_step
            if chaos is not None:
                chaos.on_step(global_step)
            if preempt is not None and preempt.requested and not stop:
                # Orchestrated preemption (SIGTERM): the in-flight step has
                # finished; drain the loader/placement ring below and let
                # train()'s finally take the awaited emergency checkpoint.
                journal.preempted = True
                logger.log({"preempted": True,
                            "at_step": journal.abs_step,
                            "epoch": epoch}, to_wandb=False)
                stop = True
            if stop:
                # max_steps / preemption mid-epoch: close the loader's
                # generator so producer threads and the placement ring
                # observe the stop flag, drain, and release their
                # BufferPool leases.
                if hasattr(it, "close"):
                    it.close()
                break
        if profiling:  # epoch shorter than the trace window
            jax.profiler.stop_trace()
            profiling = False
        # Value fetch BEFORE stopping the clock: on the tunneled TPU backend
        # block_until_ready returns early, so only the D2H fetch guarantees
        # epoch_time covers all device work.
        loss_sum_host = float(loss_sum)  # ldt: ignore[LDT1704] -- epoch-boundary fetch: the D2H is what guarantees epoch_time covers all device work
        epoch_time = time.perf_counter() - epoch_start
        steps = timer.steps
        epoch_metrics = {
            "epoch": epoch,
            "loss": loss_sum_host / max(steps, 1),
            "epoch_time": epoch_time,
            # Wall-clock rate (the final value fetch above makes epoch_time
            # cover ALL device work). The StepTimer sums only dispatch time
            # on async backends, so a timer-based rate overstates throughput;
            # the timer is kept solely for the host-side stall share.
            "images_per_sec": config.batch_size * steps / epoch_time
            if epoch_time > 0 else 0.0,
            "images_per_sec_per_chip": (
                config.batch_size * steps / epoch_time / n_devices
                if epoch_time > 0 else 0.0
            ),
            "loader_stall_pct": timer.loader_stall_pct,
        }
        # Phase-latency distribution (run-wide fixed-bucket histograms):
        # the p95/p99 tail the mean loader_stall_pct hides.
        epoch_metrics.update(timer.percentiles())
        if config.data_echo > 1:
            # Rate above counts every echoed step's batch; unique images/sec
            # is that divided by the echo factor — report both honestly.
            epoch_metrics["data_echo"] = config.data_echo
            epoch_metrics["unique_images_per_sec"] = (
                epoch_metrics["images_per_sec"] / config.data_echo
            )
        # Critical-path attribution over the epoch's in-ring spans
        # (obs/critpath.py): which segment dominated the traced batch
        # chains, plus the top-3 straggler item keys for the cost ledger.
        # Only loopback/local runs see full chains (remote roots live in
        # the server's tracer); failure-isolated — telemetry must never
        # fail an epoch.
        try:
            from .obs.critpath import analyze as _critpath_analyze
            from .obs.spans import default_tracer

            _attrs = _critpath_analyze(
                [s.to_event() for s in default_tracer().spans]
            )
            if _attrs:
                epoch_metrics["critpath_coverage_pct"] = round(
                    sum(a["coverage_pct"] for a in _attrs) / len(_attrs), 2
                )
                _dominants: dict = {}
                for a in _attrs:
                    _dominants[a["dominant"]] = (
                        _dominants.get(a["dominant"], 0) + 1
                    )
                epoch_metrics["critpath_dominant"] = max(
                    _dominants, key=_dominants.get
                )
                _stragglers = [
                    str(a["item"])[:16] for a in _attrs[:3] if a.get("item")
                ]
                if _stragglers:
                    epoch_metrics["straggler_items"] = ",".join(_stragglers)
        except Exception:  # noqa: BLE001
            pass
        if config.eval_every and (epoch + 1) % config.eval_every == 0:
            val_loader = _build_eval_loader(
                config, eval_dataset, mesh, index_pool=eval_pool,
                batch_cache=batch_cache, folder_fp=folder_fp,
            )
            epoch_metrics["val_acc"] = evaluate(state, val_loader, eval_step)
        logger.log(epoch_metrics, step=epoch)
        history.append(dict(epoch_metrics))
        results = epoch_metrics
        if (
            ckpt is not None
            and (epoch + 1) % config.checkpoint_every == 0
            and not stop
        ):
            # Epoch-boundary checkpoint — step-id'd (absolute data step,
            # monotonic across restarts) with a cursor naming the next
            # epoch's first batch. A max_steps stop mid-epoch must not
            # checkpoint the partial epoch as completed — resume would
            # silently skip its remainder (preemptions go through the
            # journal's emergency path instead).
            journal.state = state
            journal.rng = rng
            journal.cursor_base = {"epoch": epoch + 1, "step": 0}
            if ckpt.save(journal.abs_step, state,
                         cursor=journal.make_cursor()):
                journal.saved_step = journal.abs_step
        if stop:
            break

    results["history"] = history
    results["steps"] = global_step  # train steps executed this run
    results["global_step"] = journal.abs_step  # absolute, across restarts
    results["total_time"] = time.perf_counter() - total_start
    results["start_epoch"] = start_epoch
    if journal.preempted:
        results["preempted"] = True
    if config.eval_at_end and not journal.preempted:
        # Final eval — over the val split when given, else over the train
        # loader as the reference does (lance_iterable.py:125-127); all
        # processes participate since eval is itself a sharded computation.
        key = (
            "val_acc"
            if (val_dataset is not None or val_pool is not None)
            else "train_acc"
        )
        loader = _build_eval_loader(
            config, eval_dataset, mesh, index_pool=eval_pool,
            batch_cache=batch_cache, folder_fp=folder_fp,
        )
        results[key] = evaluate(state, loader, eval_step)
        logger.log({key: results[key]})
    return results
