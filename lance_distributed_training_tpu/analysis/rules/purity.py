"""Content-purity taint rule (LDT1301).

PR 9's autotuner contract — "actuation changes capacity, never content" —
and the bit-identical-stream guarantee every parity test pins are the same
invariant stated twice: the *content* of the stream (which rows land in
which batch, in what order, with what digests) must be a pure function of
(dataset, plan parameters, seed, epoch, cursor). Wall clocks, unseeded
RNG, thread identity, set-iteration order, multi-producer queue arrival
order, and live tunable values may shape *when* and *how fast* batches
move — never *what* is in them.

Before this rule that separation lived in prose and benches. Here it is
static: ``[tool.ldt-check.content-paths]`` declares the content
computations (plan generation, batch assembly, cursor arithmetic, lineage
digests) as ``path-glob[::function-glob]`` entries, and the
:class:`~..ownermodel.OwnerModel` purity pass flags every taint source
lexically inside a declared content function or any function it reaches
through resolved calls within content modules. A finding is either a real
reproducibility bug (seed the RNG, sort the iteration, derive the value
from the plan) or a reviewed-benign case — suppress those with a reasoned
``# ldt: ignore[LDT1301] -- why``; bare ignores stay live, the same
discipline as every other whole-program family.
"""

from __future__ import annotations

from typing import Iterable

from ..core import Finding, Rule, register
from ..ownermodel import build_owner_model


@register
class ContentPurityTaint(Rule):
    id = "LDT1301"
    name = "content-purity-taint"
    description = (
        "nondeterminism source (wall clock, unseeded RNG, thread identity, "
        "set/queue order, actuator setter) reachable from a declared "
        "content path"
    )
    family = "purity"
    uses_owner_model = True

    def check_program(self, program, config) -> Iterable[Finding]:
        model = build_owner_model(program, config)
        for hit in model.taints:
            where = (
                "inside" if hit.func == hit.content_root
                else f"reachable from content path {hit.content_root}"
            )
            root = hit.content_root.rsplit(".", 2)
            root_short = ".".join(root[-2:])
            yield Finding(
                self.id, hit.module, hit.line, hit.col,
                f"nondeterminism source {hit.source} {where} "
                f"(content path {root_short}) — content must be a pure "
                "function of (dataset, plan, seed, epoch, cursor); "
                "capacity/telemetry may vary, content may not "
                "(reviewed-benign uses need a reasoned "
                "`# ldt: ignore[LDT1301] -- why`)",
            )
