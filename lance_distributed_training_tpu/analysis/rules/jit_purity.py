"""Jit-purity rules (LDT101, LDT102).

A ``jax.jit``-compiled step function runs its Python body once per compile,
not once per step: ``print``/logging/wandb calls inside fire at trace time
(or worse, per-step via callbacks the author didn't intend), and host syncs
(``.item()``, ``jax.device_get``, ``np.asarray`` on traced values, casting a
traced argument with ``float()``/``int()``) either fail at trace time or —
when they survive — serialize the device stream against the host in the hot
loop, which is exactly the stall class the StepTimer exists to keep under 2%.
Telemetry belongs outside the jitted function, on fetched outputs.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from ..core import Finding, ModuleInfo, Rule, register

_JIT_NAMES = {"jax.jit", "jit", "jax.pmap", "pmap"}
_LOG_ROOTS = {"logging", "wandb"}
_LOGGERY = {"logger", "log", "_logger", "_log"}
# A logger-named variable only counts with a logging verb: `log.sum()` on a
# local named `log` (e.g. log = jnp.log(p)) is math, not telemetry.
_LOG_VERBS = {"debug", "info", "warning", "warn", "error", "exception",
              "critical", "log"}
_HOST_SYNC_CALLS = {
    "jax.device_get", "numpy.asarray", "numpy.array", "numpy.copy",
}
_CLOCKS = {"time.time", "time.perf_counter", "time.monotonic",
           "time.time_ns"}


def _is_jit_expr(module: ModuleInfo, node: ast.AST) -> bool:
    """Is ``node`` (a decorator or a call's func) a jit wrapper? Covers
    ``jax.jit``, ``@partial(jax.jit, ...)`` and ``jax.jit(...)`` calls."""
    qn = module.qualname(node)
    if qn in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fq = module.qualname(node.func)
        if fq in _JIT_NAMES:
            return True
        if fq in ("functools.partial", "partial") and node.args:
            return module.qualname(node.args[0]) in _JIT_NAMES
    return False


def _jitted_functions(module: ModuleInfo) -> List[ast.AST]:
    """FunctionDefs/Lambdas that end up inside jax.jit:

    * decorated: ``@jax.jit`` / ``@partial(jax.jit, ...)``;
    * wrapped by name: ``jax.jit(step, ...)`` marks the ``def step`` in the
      same module (nearest definition by name);
    * wrapped inline: ``jax.jit(lambda ...: ...)``.
    """
    by_name = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    out: List[ast.AST] = []
    seen: Set[int] = set()

    def add(fn: ast.AST) -> None:
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(fn)

    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(module, dec):
                    add(node)
        elif isinstance(node, ast.Call) and _is_jit_expr(module, node.func):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Lambda):
                    add(arg)
                elif isinstance(arg, ast.Name):
                    for fn in by_name.get(arg.id, []):
                        add(fn)
    return out


def _params_of(fn: ast.AST) -> Set[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return set(names)


@register
class JitSideEffect(Rule):
    id = "LDT101"
    family = "jit-purity"
    name = "jit-side-effect"
    description = (
        "print/logging/wandb/clock call inside a jax.jit-compiled function "
        "— side effects fire at trace time, not per step"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        for fn in _jitted_functions(module):
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    qn = module.qualname(node.func) or ""
                    root = qn.split(".", 1)[0]
                    leaf = qn.rsplit(".", 1)[-1]
                    offender = None
                    if qn == "print":
                        offender = "print()"
                    elif "." in qn and (
                        root in _LOG_ROOTS
                        or (root in _LOGGERY and leaf in _LOG_VERBS)
                    ):
                        offender = f"{qn}()"
                    elif qn in _CLOCKS:
                        offender = f"{qn}()"
                    if offender:
                        yield Finding(
                            self.id, module.relpath,
                            node.lineno, node.col_offset,
                            f"{offender} inside a jit-compiled function "
                            "runs at trace time, not per step — move "
                            "telemetry outside the jitted step (or use "
                            "jax.debug.print deliberately)",
                        )


@register
class JitHostSync(Rule):
    id = "LDT102"
    family = "jit-purity"
    name = "jit-host-sync"
    description = (
        ".item()/jax.device_get/np.asarray/float() on traced values inside "
        "jax.jit — host syncs in the compiled hot path"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        for fn in _jitted_functions(module):
            params = _params_of(fn)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    offender = None
                    qn = module.qualname(node.func) or ""
                    if (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and not node.args
                    ):
                        offender = ".item()"
                    elif qn in _HOST_SYNC_CALLS:
                        offender = f"{qn}()"
                    elif (
                        qn in ("float", "int", "bool")
                        and len(node.args) == 1
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id in params
                    ):
                        # Casting a traced ARGUMENT is a definite host sync;
                        # float(config.lr)-style casts of static values are
                        # fine, so only parameter names are flagged.
                        offender = f"{qn}({node.args[0].id})"
                    if offender:
                        yield Finding(
                            self.id, module.relpath,
                            node.lineno, node.col_offset,
                            f"{offender} inside a jit-compiled function "
                            "forces a device→host sync (or a trace error); "
                            "return the value and convert it outside the "
                            "step",
                        )
