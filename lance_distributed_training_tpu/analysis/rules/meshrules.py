"""Device-semantics rules (LDT1701-1704).

The compute plane's XLA-facing contract is exactly what the compiler does
not check — these rules consume the whole-program
:class:`~..meshmodel.MeshModel` and machine-check it the way LDT14xx
checks the wire contract:

* **LDT1701 undeclared-axis** — a ``PartitionSpec`` or collective names an
  axis outside the declared mesh vocabulary (``[tool.ldt-check]
  mesh-axes``, seeded from ``parallel/mesh.py``). A typo'd ``"dtaa"``
  compiles fine and silently replicates instead of sharding.
* **LDT1702 use-after-donate** — a value passed in a donated position
  (``donate_argnums``) is read again on any path after the call,
  interprocedurally: the donated buffer now holds whatever XLA scribbled
  into it.
* **LDT1703 recompile hazard** — a batch-content-derived Python value
  (``.shape``, ``len()``) reaches a ``static_argnames``/``static_argnums``
  position, or a Python branch on a parameter shape sits inside a jitted
  content-path function; either keys the jit cache per batch. Derivations
  routed through a declared quantized funnel (``static-funnels``) are
  sanctioned — they clamp the key ladder to O(1).
* **LDT1704 hot-path host sync** — ``.item()`` / ``float()`` / ``int()``
  / ``bool()`` / ``np.asarray`` on a device-derived value in a declared
  ``device-hot-paths`` module outside jitted bodies and ``sync-funnels``
  — each one serialises the async dispatch stream.

Like the other whole-program families, a suppression needs a
``-- reason``; bare ignores stay live. The runtime witness
(``LDT_COMPILE_SANITIZER=1`` + ``ldt check --compile-witness``)
corroborates or prunes LDT1703 exactly like the leak witness does
LDT1201: a hazard whose jit site demonstrably recompiled after warmup in
an instrumented run is *reproduced*; one whose site was exercised with a
single steady-state compile is ``witness_pruned`` (rendered, not failing,
never baselined).
"""

from __future__ import annotations

from typing import Iterable

from ..core import Finding, Rule, register
from ..meshmodel import build_mesh_model


@register
class UndeclaredAxis(Rule):
    id = "LDT1701"
    name = "undeclared-mesh-axis"
    description = (
        "PartitionSpec/collective names a mesh axis outside the declared "
        "[tool.ldt-check] mesh-axes vocabulary — a typo'd axis silently "
        "replicates instead of sharding"
    )
    family = "mesh"
    uses_mesh_model = True

    def check_program(self, program, config) -> Iterable[Finding]:
        model = build_mesh_model(program, config)
        declared = set(model.mesh_axes)
        for ref in model.axis_refs:
            if ref.axis in declared:
                continue
            yield Finding(
                self.id, ref.module, ref.line, ref.col,
                f"axis {ref.axis!r} in {ref.context} is not in the declared "
                f"mesh vocabulary {sorted(declared)} — a misspelt axis "
                f"compiles fine and silently replicates; fix the name or "
                f"declare it in [tool.ldt-check] mesh-axes",
            )


@register
class UseAfterDonate(Rule):
    id = "LDT1702"
    name = "use-after-donate"
    description = (
        "value passed in a donate_argnums position is read again after "
        "the call — the donated buffer now holds whatever XLA wrote into it"
    )
    family = "mesh"
    uses_mesh_model = True

    def check_program(self, program, config) -> Iterable[Finding]:
        model = build_mesh_model(program, config)
        for h in model.donate_hazards:
            tail = (
                "re-read on the next loop iteration"
                if h.read_line == h.line
                else f"read again at line {h.read_line}"
            )
            yield Finding(
                self.id, h.module, h.line, h.col,
                f"{h.var!r} is donated to {h.callee!r} (donate_argnums) but "
                f"{tail} — the buffer is consumed by XLA at the call; "
                f"rebind the name from the call's result or drop the "
                f"donation",
            )


@register
class RecompileHazardRule(Rule):
    id = "LDT1703"
    name = "recompile-hazard"
    description = (
        "batch-content-derived Python value (.shape/len, outside the "
        "declared quantized funnels) reaches a jit static position or a "
        "Python branch inside a jitted content-path function — the jit "
        "cache keys per batch"
    )
    family = "mesh"
    uses_mesh_model = True

    def check_program(self, program, config) -> Iterable[Finding]:
        model = build_mesh_model(program, config)
        witness = getattr(config, "compile_witness", None)
        for h in model.recompile_hazards:
            message = (
                f"{h.detail} — every distinct value compiles a new "
                f"executable; route it through a declared quantized funnel "
                f"(static-funnels) or hoist the branch out of the batch "
                f"path"
            )
            pruned = False
            if witness:
                verdict = model.witness_verdict(h.site, witness)
                if verdict == "reproduced":
                    message += (
                        " [witness: this jit site recompiled after warmup "
                        "in the instrumented run — a reproduced recompile, "
                        "not an inference]"
                    )
                elif verdict == "pruned":
                    pruned = True
                    message += (
                        " [witness_pruned: this jit site was exercised in "
                        "the instrumented run with no post-warmup "
                        "recompiles]"
                    )
            yield Finding(
                self.id, h.module, h.line, h.col, message,
                witness_pruned=pruned,
            )


@register
class HotPathHostSync(Rule):
    id = "LDT1704"
    name = "hot-path-host-sync"
    description = (
        ".item()/float()/int()/bool()/np.asarray on a device-derived value "
        "in a device-hot-paths module — serialises the async dispatch "
        "stream outside the declared sync funnels"
    )
    family = "mesh"
    uses_mesh_model = True

    def check_program(self, program, config) -> Iterable[Finding]:
        model = build_mesh_model(program, config)
        for h in model.host_syncs:
            yield Finding(
                self.id, h.module, h.line, h.col,
                f"{h.expr} forces a device→host sync on the hot path "
                f"({h.func}) — it blocks until every queued computation "
                f"lands; keep values on device, batch the fetch, or declare "
                f"a sync funnel (sync-funnels) for a deliberate D2H site",
            )
