"""Compat-enforcement rule (LDT401).

The seed's single worst failure was 14 test modules dying at collection
because ``jax.experimental.shard_map`` moved between jax releases.
``parallel/_compat.py`` now owns every version-moved symbol (``shard_map``,
``pcast``, ``axis_size``) behind feature-detection; this rule makes the fix
permanent by rejecting any direct import or attribute use of those symbols
from jax anywhere else in the package.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, ModuleInfo, Rule, register


@register
class DirectCompatImport(Rule):
    id = "LDT401"
    family = "compat"
    name = "direct-compat-import"
    description = (
        "version-moved jax symbol (shard_map/pcast/axis_size) imported or "
        "used directly outside parallel/_compat.py"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        if module.relpath == config.compat_module:
            return
        symbols = set(config.compat_symbols)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level:  # relative import — can only be the shim
                    continue
                if mod == "jax" or mod.startswith("jax."):
                    for alias in node.names:
                        if alias.name in symbols or mod.rsplit(
                            ".", 1
                        )[-1] in symbols:
                            yield Finding(
                                self.id, module.relpath,
                                node.lineno, node.col_offset,
                                f"direct import of {alias.name!r} from "
                                f"{mod!r} — this symbol moved between jax "
                                "releases and broke package-wide import "
                                "once already; import it from "
                                f"{config.compat_module} instead",
                            )
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith("jax") and alias.name.rsplit(
                        ".", 1
                    )[-1] in symbols:
                        yield Finding(
                            self.id, module.relpath,
                            node.lineno, node.col_offset,
                            f"direct import of {alias.name!r} — import the "
                            f"symbol from {config.compat_module} instead",
                        )
            elif isinstance(node, ast.Attribute):
                if node.attr not in symbols:
                    continue
                qn = module.qualname(node)
                if qn and (
                    qn.startswith("jax.") or qn.startswith("jax.lax.")
                ):
                    # hasattr(lax, "...") probes are string-based and never
                    # reach here; a real attribute use does.
                    yield Finding(
                        self.id, module.relpath,
                        node.lineno, node.col_offset,
                        f"direct use of {qn} — version-moved jax API; use "
                        f"the shim in {config.compat_module}",
                    )
