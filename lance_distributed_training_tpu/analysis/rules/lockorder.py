"""Cross-module lock-order rule (LDT1001).

A deadlock needs two facts that usually live in two files: thread 1 holds
lock A and wants B (say, the coordinator's lease-table lock and then the
metrics registry's), thread 2 holds B and wants A. Per-module AST rules are
structurally blind to it. This rule consumes the shared
:class:`~..concmodel.ProgramInfo` lock-order graph — an edge ``A → B`` for
every site where B is acquired while A is held (nested ``with``, a call
chain entered under A, or a function the fixpoint proves is only ever
called with A held) — and reports every elementary cycle, plus non-reentrant
re-acquisition (``with self._lock`` inside a frame already holding it: a
one-thread deadlock, no second thread required).

Static inference can report cycles whose edges never co-occur at runtime
(infeasible paths). The runtime witness closes that gap: run the test suite
with ``LDT_LOCK_SANITIZER=1`` (``utils/lockorder.py``) and hand the emitted
edge file to ``ldt check --lock-witness``. A cycle containing an edge that
the instrumented run *never observed* — while both locks demonstrably were
exercised — is marked ``witness_pruned`` (rendered, but neither failing the
gate nor baselined); a cycle whose every edge was observed gains the
runtime corroboration in its message, turning "potential" into
"reproduced".
"""

from __future__ import annotations

from typing import Iterable, List

from ..core import Finding, Rule, register


@register
class LockOrderCycles(Rule):
    id = "LDT1001"
    name = "lock-order-cycle"
    description = (
        "cross-module lock acquisition cycle (potential deadlock) or "
        "non-reentrant re-acquisition of a held lock"
    )
    family = "lock-order"

    def check_program(self, program, config) -> Iterable[Finding]:
        witness = getattr(config, "lock_witness", None)
        for cycle in program.lock_cycles():
            head = cycle[0]
            if len(cycle) == 1 and head.src == head.dst:
                yield Finding(
                    self.id, head.module, head.line, head.col,
                    f"non-reentrant lock {program.lock_display(head.src)} "
                    f"acquired while already held ({head.via}) — this "
                    "thread deadlocks against itself; use RLock or narrow "
                    "the outer critical section",
                )
                continue
            chain = " -> ".join(
                f"{program.lock_display(e.src)}"
                f" ({e.module}:{e.line}, {self._short_via(e.via)})"
                for e in cycle
            )
            closing = program.lock_display(cycle[0].src)
            message = (
                f"lock-order cycle ({len(cycle)} locks): {chain} -> "
                f"{closing} — two threads interleaving these acquisitions "
                "deadlock; pick one global order or drop a lock scope"
            )
            pruned = False
            if witness:
                verdict = self._witness_verdict(program, cycle, witness)
                if verdict == "pruned":
                    pruned = True
                    message += (
                        " [witness_pruned: an edge of this cycle was never "
                        "observed in the instrumented run although both "
                        "locks were exercised]"
                    )
                elif verdict == "confirmed":
                    message += (
                        " [witness: every edge of this cycle was observed "
                        "at runtime — this is a reproduced ordering, not "
                        "an inference]"
                    )
            yield Finding(
                self.id, head.module, head.line, head.col, message,
                witness_pruned=pruned,
            )

    @staticmethod
    def _short_via(via: str) -> str:
        return via if len(via) <= 64 else via[:61] + "..."

    @staticmethod
    def _witness_verdict(program, cycle, witness) -> str:
        """"pruned" | "confirmed" | "unknown" for a static cycle against
        the observed-edge set. Pruning is deliberately strict: it needs
        BOTH locks of the missing edge to have been exercised at runtime —
        absence of evidence about an untouched lock proves nothing."""
        observed_edges = witness.get("edges", set())
        acquired = witness.get("acquired", {})

        def sites(lock_key) -> List[str]:
            info = program.locks.get(lock_key)
            return list(info.sites) if info is not None else []

        def exercised(lock_key) -> bool:
            return any(s in acquired for s in sites(lock_key))

        def observed(edge) -> bool:
            return any(
                (s_src, s_dst) in observed_edges
                for s_src in sites(edge.src)
                for s_dst in sites(edge.dst)
            )

        all_observed = True
        for edge in cycle:
            if observed(edge):
                continue
            all_observed = False
            if exercised(edge.src) and exercised(edge.dst):
                return "pruned"
        return "confirmed" if all_observed else "unknown"
