"""Graph-hygiene rule (LDT1601).

The r16 unified loader graph (``data/graph.py``) exists because five
parallel source→decode→batch pipelines each had to be re-wired for every
new plane (cache, device-decode, token-pack, placement). The cheapest way
to regress to that world is one innocent-looking construction: a hot-path
module building a ``DataPipeline``/``MapStylePipeline``/
``FolderDataPipeline``/``RemoteLoader``/``FleetLoader`` directly instead of
composing a ``LoaderGraph`` — a sixth parallel loader nobody notices until
the next plane has to be wired six times.

Scoped to the ``hot-paths`` modules from ``[tool.ldt-check]``, with the
engine home modules exempt: ``data/pipeline.py`` and ``data/folder.py``
legitimately build inner ``DataPipeline`` instances (the per-epoch engine
beneath the map-style/folder loaders), ``service/client.py`` and
``fleet/balancer.py`` ARE the transport engines, and ``data/graph.py`` is
the one compile seam allowed to construct all five. Everywhere else, a
loader is a ``LoaderGraph`` composition; a deliberate exception can still
be grandfathered in the baseline or carry a reasoned
``# ldt: ignore[LDT1601]``.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterable

from ..core import Finding, ModuleInfo, Rule, register

# The five engine classes whose direct construction means "a new parallel
# loader is being written".
_ENGINES = frozenset({
    "DataPipeline",
    "MapStylePipeline",
    "FolderDataPipeline",
    "RemoteLoader",
    "FleetLoader",
})

# Engine home modules (see module docstring) + the graph compile seam.
_EXEMPT = (
    "*data/pipeline.py",
    "*data/folder.py",
    "*data/graph.py",
    "*service/client.py",
    "*fleet/balancer.py",
)


@register
class GraphHygiene(Rule):
    id = "LDT1601"
    family = "graph"
    name = "graph-hygiene"
    description = (
        "hot-path modules: no direct construction of the five loader "
        "engines (DataPipeline/MapStylePipeline/FolderDataPipeline/"
        "RemoteLoader/FleetLoader) outside their home modules and "
        "data/graph.py — source→decode→batch compositions are LoaderGraph "
        "assemblies, so every new plane is wired exactly once"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        hot_paths = getattr(config, "hot_paths", [])
        if not any(fnmatch.fnmatch(module.relpath, p) for p in hot_paths):
            return
        if any(fnmatch.fnmatch(module.relpath, p) for p in _EXEMPT):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = None
            if isinstance(func, ast.Name):
                name = func.id
            elif isinstance(func, ast.Attribute):
                name = func.attr
            if name in _ENGINES:
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    f"{name}(...) constructed outside the loader graph — "
                    "compose a data/graph.py LoaderGraph (Source → Decode "
                    "→ Cache/Pool/Buffers/Prefetch → Transport → Place) "
                    "instead of wiring a parallel pipeline; the engines "
                    "are the graph's compile targets, not an API",
                )
