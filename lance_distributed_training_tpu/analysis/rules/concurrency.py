"""Concurrency-hygiene rules (LDT201-LDT203).

The loader/service stack is a web of producer threads and bounded queues
whose shutdown discipline (daemon flag + drain-then-join) and backpressure
contract (every queue bounded) were established the hard way in PR 1. These
rules keep the discipline structural:

* LDT201 — every ``threading.Thread(...)`` must state its lifecycle: either
  an explicit ``daemon=`` (this repo's policy is daemon=True + the
  drain-join pattern, see ``data/pipeline.py``) or a tracked ``.join()``.
* LDT202 — ``queue.Queue()`` with no ``maxsize`` in the streaming paths is
  an unbounded buffer: one slow consumer absorbs the whole epoch in RAM.
* LDT203 — a handshake ``recv`` with no prior ``settimeout`` pins a handler
  thread forever when a peer connects and goes silent.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterable, Optional

from ..core import Finding, ModuleInfo, Rule, register

_QUEUE_CTORS = {
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "multiprocessing.Queue", "multiprocessing.JoinableQueue",
}
_RECV_NAMES = {"recv", "recv_into", "recvfrom", "recv_msg", "recv_frame"}
_HELLO_MARKERS = ("HELLO", "handshake")


@register
class ThreadLifecycle(Rule):
    id = "LDT201"
    family = "concurrency"
    name = "thread-lifecycle"
    description = (
        "threading.Thread without an explicit daemon= and without a "
        "tracked .join() — its shutdown story is undefined"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if module.qualname(node.func) != "threading.Thread":
                continue
            if any(kw.arg == "daemon" for kw in node.keywords):
                continue
            target = self._assign_target(module, node)
            if target is not None and self._joined(module, node, target):
                continue
            yield Finding(
                self.id, module.relpath, node.lineno, node.col_offset,
                "threading.Thread(...) without daemon= or a .join() path — "
                "state the lifecycle: daemon=True + drain-join on teardown "
                "(this repo's policy), or keep a handle and join it",
            )

    @staticmethod
    def _assign_target(module: ModuleInfo, node: ast.Call) -> Optional[str]:
        """Name (or self-attribute name) the Thread is bound to, if simple."""
        stmt = module.statement_of(node)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            t = stmt.targets[0]
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, ast.Attribute):
                return t.attr
        return None

    @staticmethod
    def _joined(module: ModuleInfo, node: ast.Call, target: str) -> bool:
        scope = module.enclosing(
            node, (ast.ClassDef, ast.Module)
        ) or module.tree
        for n in ast.walk(scope):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "join"
            ):
                owner = n.func.value
                name = owner.id if isinstance(owner, ast.Name) else (
                    owner.attr if isinstance(owner, ast.Attribute) else None
                )
                if name == target:
                    return True
        return False


@register
class UnboundedQueue(Rule):
    id = "LDT202"
    family = "concurrency"
    name = "unbounded-queue"
    description = (
        "queue.Queue() without maxsize on a streaming path — voids the "
        "backpressure contract (one slow consumer buffers the whole epoch)"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        patterns = getattr(config, "queue_paths", [])
        if patterns and not any(
            fnmatch.fnmatch(module.relpath, p) for p in patterns
        ):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if module.qualname(node.func) not in _QUEUE_CTORS:
                continue
            if self._bounded(node):
                continue
            yield Finding(
                self.id, module.relpath, node.lineno, node.col_offset,
                "unbounded queue on a streaming path (stdlib semantics: "
                "maxsize<=0 means infinite) — pass maxsize>=1 so "
                "backpressure reaches the producer instead of buffering "
                "the epoch in RAM",
            )

    @staticmethod
    def _bounded(node: ast.Call) -> bool:
        """A queue is bounded only when maxsize is present AND not a
        literal <= 0 — ``Queue(0)`` / ``Queue(maxsize=0)`` are the stdlib
        spelling of *infinite*, the exact thing this rule exists to catch.
        Non-literal maxsize expressions get the benefit of the doubt."""
        maxsize = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "maxsize"), None
        )
        if maxsize is None:
            return False
        if isinstance(maxsize, ast.Constant) and isinstance(
            maxsize.value, (int, float)
        ):
            return maxsize.value > 0
        if isinstance(maxsize, ast.UnaryOp) and isinstance(
            maxsize.op, ast.USub
        ):
            return False  # any negative literal is unbounded too
        return True


@register
class HandshakeRecvTimeout(Rule):
    id = "LDT203"
    family = "concurrency"
    name = "handshake-recv-timeout"
    description = (
        "blocking recv on a handshake path with no prior settimeout — a "
        "peer that connects and goes silent pins the handler forever"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not self._is_handshake(module, fn):
                continue
            first_recv: Optional[ast.Call] = None
            first_timeout_line: Optional[int] = None
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                attr = (
                    node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else (node.func.id if isinstance(node.func, ast.Name)
                          else None)
                )
                qn = module.qualname(node.func) or ""
                leaf = qn.rsplit(".", 1)[-1]
                if (attr in _RECV_NAMES or leaf in _RECV_NAMES) and (
                    first_recv is None or node.lineno < first_recv.lineno
                ):
                    first_recv = node
                if attr == "settimeout" and (
                    first_timeout_line is None
                    or node.lineno < first_timeout_line
                ):
                    first_timeout_line = node.lineno
            if first_recv is None:
                continue
            if self._deadline_bounded(first_recv):
                # recv_msg(sock, deadline=...) bounds the WHOLE frame read
                # (protocol._recv_exact) — strictly stronger than a socket
                # settimeout, which resets per received byte.
                continue
            if (
                first_timeout_line is None
                or first_timeout_line > first_recv.lineno
            ):
                yield Finding(
                    self.id, module.relpath,
                    first_recv.lineno, first_recv.col_offset,
                    f"handshake function {fn.name!r} blocks in recv with no "
                    "prior settimeout — a connected-but-silent peer pins "
                    "this thread forever; set a handshake deadline, then "
                    "clear it for the streaming phase",
                )

    @staticmethod
    def _deadline_bounded(recv: ast.Call) -> bool:
        for kw in recv.keywords:
            if kw.arg == "deadline" and not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            ):
                return True
        return False

    @staticmethod
    def _is_handshake(module: ModuleInfo, fn: ast.AST) -> bool:
        """A function is handshake-shaped when its name or body mentions the
        HELLO frame / 'handshake'. Narrow on purpose: steady-state stream
        receive loops have different deadline semantics (a slow decode is
        not a dead peer) and must not be forced onto a timeout."""
        if any(m.lower() in fn.name.lower() for m in _HELLO_MARKERS):
            return True
        for node in ast.walk(fn):
            name = None
            if isinstance(node, ast.Attribute):
                name = node.attr
            elif isinstance(node, ast.Name):
                name = node.id
            if name and any(m in name for m in _HELLO_MARKERS):
                return True
        return False
