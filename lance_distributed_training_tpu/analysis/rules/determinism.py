"""Determinism rules (LDT001-LDT003).

The epoch ``Plan`` must be a pure function of (dataset, sampler, batch,
shard, seed, epoch): every process builds all shards' plans and asserts
equal step counts, and the disaggregated service rebuilds the same plan from
the client's handshake. Any global-state randomness, wall-clock seeding, or
filesystem-order dependence in that path breaks bit-identical resume,
cross-process agreement, and A/B benchmarks — silently.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, ModuleInfo, Rule, register

# Global-state RNG entry points. Seeded `default_rng(seed)` / `Generator`
# methods are the sanctioned API and never match these.
_NP_GLOBAL = {
    "seed", "rand", "randn", "randint", "random", "random_sample", "choice",
    "shuffle", "permutation", "uniform", "normal", "standard_normal", "bytes",
}
_STDLIB_RANDOM = {
    "seed", "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "getrandbits", "randbytes",
}

_CLOCKS = {
    "time.time", "time.time_ns", "datetime.datetime.now",
    "datetime.datetime.utcnow", "datetime.date.today",
}

_PLANNY = ("seed", "plan", "shuffle", "permut", "sampler")

_LISTING = {"os.listdir", "glob.glob", "glob.iglob", "os.scandir"}
_LISTING_METHODS = {"glob", "iglob", "iterdir", "rglob"}  # pathlib-style


@register
class UnseededGlobalRng(Rule):
    id = "LDT001"
    family = "determinism"
    name = "unseeded-global-rng"
    description = (
        "np.random.* / random.* global-state call — plan and shuffle "
        "randomness must come from a seeded np.random.default_rng(...)"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            qn = module.qualname(node.func)
            if qn is None:
                continue
            bad = (
                (qn.startswith("numpy.random.")
                 and qn.rsplit(".", 1)[1] in _NP_GLOBAL)
                or (qn.startswith("random.")
                    and qn.count(".") == 1
                    and qn.rsplit(".", 1)[1] in _STDLIB_RANDOM)
            )
            if bad:
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    f"global-state RNG call {qn}(); use a seeded "
                    "np.random.default_rng(seed) so plans/shuffles are "
                    "reproducible across processes and resumes",
                )


@register
class WallClockSeed(Rule):
    id = "LDT002"
    family = "determinism"
    name = "wall-clock-seed"
    description = (
        "time.time()/datetime.now() feeding seed/plan/shuffle construction "
        "— wall-clock seeds diverge across processes and resumes"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            qn = module.qualname(node.func)
            if qn not in _CLOCKS:
                continue
            sink = self._plan_sink(module, node)
            if sink:
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    f"{qn}() flows into {sink} — wall-clock values are "
                    "different on every process and every resume; derive "
                    "seeds from config.seed instead",
                )

    @staticmethod
    def _plan_sink(module: ModuleInfo, node: ast.AST):
        """Does this clock call feed plan/seed/shuffle construction?
        Detected via the enclosing statement: an assignment to a *seed*-named
        target, or an argument position of a *seed/plan/shuffle*-named call
        or keyword."""
        cur = node
        parent = module.parents.get(cur)
        while parent is not None and not isinstance(parent, ast.stmt):
            if isinstance(parent, ast.keyword) and parent.arg:
                if any(p in parent.arg.lower() for p in _PLANNY):
                    return f"keyword {parent.arg}="
            if isinstance(parent, ast.Call) and parent is not cur:
                qn = module.qualname(parent.func) or ""
                leaf = qn.rsplit(".", 1)[-1].lower()
                if any(p in leaf for p in _PLANNY):
                    return f"{qn}()"
            cur = parent
            parent = module.parents.get(cur)
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                parent.targets if isinstance(parent, ast.Assign)
                else [parent.target]
            )
            for t in targets:
                name = t.id if isinstance(t, ast.Name) else (
                    t.attr if isinstance(t, ast.Attribute) else ""
                )
                if any(p in name.lower() for p in _PLANNY):
                    return f"assignment to {name!r}"
        return None


@register
class UnsortedListing(Rule):
    id = "LDT003"
    family = "determinism"
    name = "unsorted-fs-listing"
    description = (
        "os.listdir/glob results used without sorted() — filesystem order "
        "is platform- and mount-dependent, so sample lists built from it "
        "differ across hosts"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            qn = module.qualname(node.func)
            is_listing = qn in _LISTING or (
                qn is None
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _LISTING_METHODS
            )
            if not is_listing:
                continue
            if self._ordered_or_orderless(module, node):
                continue
            what = qn or f".{node.func.attr}"  # type: ignore[union-attr]
            yield Finding(
                self.id, module.relpath, node.lineno, node.col_offset,
                f"{what}() result used without sorted() — directory order "
                "is nondeterministic across hosts/filesystems; wrap in "
                "sorted(...) before building sample lists",
            )

    @staticmethod
    def _ordered_or_orderless(module: ModuleInfo, node: ast.Call) -> bool:
        """True when the listing is sorted in-expression, explicitly sorted
        later, or used where order cannot matter (membership test, len)."""
        cur: ast.AST = node
        parent = module.parents.get(cur)
        assigned_to = None
        while parent is not None and not isinstance(parent, ast.stmt):
            if isinstance(parent, ast.Call):
                pq = module.qualname(parent.func) or ""
                if pq.rsplit(".", 1)[-1] in ("sorted", "len", "set",
                                             "frozenset", "Counter"):
                    return True
            if isinstance(parent, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in parent.ops
            ):
                return True
            cur = parent
            parent = module.parents.get(cur)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            t = parent.targets[0]
            if isinstance(t, ast.Name):
                assigned_to = t.id
        if assigned_to:
            func = module.enclosing(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)
            )
            scope = func if func is not None else module.tree
            for n in ast.walk(scope):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "sort"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == assigned_to
                ):
                    return True
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == "sorted"
                    and n.args
                    and isinstance(n.args[0], ast.Name)
                    and n.args[0].id == assigned_to
                ):
                    return True
        return False
