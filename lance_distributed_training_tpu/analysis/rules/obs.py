"""Observability-hygiene rule (LDT601).

Telemetry is only as trustworthy as its clocks and its names. Two failure
classes this rule gates, scoped to the *instrumented* modules (the
``obs-paths`` config — the obs/ package, StepTimer/ServiceCounters, the
data pipeline, and both halves of the service):

* **wall-clock durations** — ``time.time()`` is not monotonic (NTP slews,
  steps backwards on clock sync), so a duration measured with it can be
  negative or wildly wrong exactly when a fleet host's clock is being
  corrected — which is also exactly when you're staring at latency
  telemetry. Instrumented modules must use ``time.perf_counter`` /
  ``time.monotonic_ns`` for durations; epoch *stamps* that intentionally
  cross process boundaries use ``time.time_ns()`` (an integer timestamp,
  not a duration — see ``obs/lineage.py``'s clock policy).
* **invalid metric names** — every name handed to a registry factory
  (``.counter(…)`` / ``.gauge(…)`` / ``.histogram(…)``) must match
  ``[a-z][a-z0-9_]*`` so it is a valid Prometheus series name as-is; a bad
  name surfaces as a scrape-time parse error on a dashboard, far from the
  line that minted it.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterable

from ..core import Finding, ModuleInfo, Rule, register
# The lint enforces the registry's own runtime rule — one regex, one place
# (obs.registry is stdlib-only, so this import carries no jax baggage).
from ...obs.registry import METRIC_NAME_RE as _METRIC_NAME_RE
# Registry get-or-create factories whose first argument is the series name.
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}


@register
class ObsHygiene(Rule):
    id = "LDT601"
    family = "obs"
    name = "obs-hygiene"
    description = (
        "instrumented modules: no time.time() (durations need "
        "perf_counter/monotonic_ns; stamps use time_ns), and metric names "
        "must match [a-z][a-z0-9_]*"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        obs_paths = getattr(config, "obs_paths", [])
        if not any(fnmatch.fnmatch(module.relpath, p) for p in obs_paths):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            qn = module.qualname(node.func)
            if qn == "time.time":
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    "time.time() in an instrumented module — wall clocks "
                    "slew/step under NTP, corrupting measured durations; "
                    "use time.perf_counter()/time.monotonic_ns() for "
                    "durations (time.time_ns() only for cross-process "
                    "epoch stamps)",
                )
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_FACTORIES
            ):
                name_arg = None
                if node.args:
                    name_arg = node.args[0]
                else:
                    for kw in node.keywords:
                        if kw.arg == "name":
                            name_arg = kw.value
                            break
                if (
                    isinstance(name_arg, ast.Constant)
                    and isinstance(name_arg.value, str)
                    and not _METRIC_NAME_RE.match(name_arg.value)
                ):
                    yield Finding(
                        self.id, module.relpath,
                        node.lineno, node.col_offset,
                        f"metric name {name_arg.value!r} does not match "
                        "[a-z][a-z0-9_]* — it would not be a valid "
                        "Prometheus series name at scrape time",
                    )
