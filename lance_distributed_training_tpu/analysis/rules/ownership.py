"""Resource ownership/lifecycle rules (LDT1201-1203).

The zero-copy buffer plane and the service/fleet transports run on a
lease-release discipline: a BufferPool page, a shm slot token, a socket, a
joinable thread each have exactly one owner at a time, and every exit path
— including the exception edges and generator closes the loader-graph
refactor will reshuffle — must either release the handle or visibly hand
it to the next owner. LDT301 checks the *shape* of ownership one statement
at a time; these rules consume the interprocedural
:class:`~..ownermodel.OwnerModel` dataflow and check the *paths*:

* **LDT1201 leak-on-path** — some exit (an early return, a statement that
  can raise while the handle is held, a generator ``close()`` at a
  ``yield``) leaves the resource acquired and neither released nor
  transferred. Reported at the acquire site.
* **LDT1202 double-release** — a release reaches a handle that may already
  be released on some path (skipped for kinds whose release is documented
  idempotent: ``BufferPool.release`` ignores foreign pages, ``close()`` is
  re-callable; a shm token double-put hands one slot to two writers).
* **LDT1203 use-after-release** — any use of the handle on a path where it
  may already be released (``sock.shutdown`` after ``close``, touching a
  released pool page the sweep may already have recycled).

Like the other LDT1xxx whole-program families, a suppression needs a
``-- reason``; bare ignores stay live. The runtime witness
(``LDT_LEAK_SANITIZER=1`` + ``ldt check --leak-witness``) corroborates or
prunes LDT1201 exactly like the lock witness does LDT1001: a leak whose
acquire site demonstrably leaked in an instrumented run is *reproduced*;
one whose site was exercised and always balanced is ``witness_pruned``
(rendered, not failing, never baselined).
"""

from __future__ import annotations

from typing import Iterable

from ..core import Finding, Rule, register
from ..ownermodel import build_owner_model

_CHANNEL_TEXT = {
    "exception": (
        "a statement that can raise while the handle is held exits the "
        "function without releasing it"
    ),
    "generator-close": (
        "an early generator close() (GeneratorExit at a yield) exits "
        "without releasing it"
    ),
    "return": (
        "a return/fall-off path exits without releasing or transferring it"
    ),
}


@register
class OwnershipLeak(Rule):
    id = "LDT1201"
    name = "resource-leak-on-path"
    description = (
        "acquired resource (pool lease, shm token, socket, thread, "
        "autotuner) held at a function exit path with no release/transfer"
    )
    family = "ownership"
    uses_owner_model = True

    def check_program(self, program, config) -> Iterable[Finding]:
        model = build_owner_model(program, config)
        witness = getattr(config, "leak_witness", None)
        for rec in model.records:
            if rec.leak is None:
                continue
            spec = model.spec(rec.kind)
            channel = _CHANNEL_TEXT.get(rec.leak, rec.leak)
            message = (
                f"{spec.describe or rec.kind} acquired into {rec.var!r} may "
                f"leak: {channel} — release in a finally, use a with block, "
                f"or transfer ownership (return / queue.put / publish on "
                f"self) before the exit"
            )
            pruned = False
            if witness:
                verdict = self._witness_verdict(rec, witness)
                if verdict == "reproduced":
                    message += (
                        " [witness: leases from this site were still held "
                        "at process exit in the instrumented run — a "
                        "reproduced leak, not an inference]"
                    )
                elif verdict == "pruned":
                    pruned = True
                    message += (
                        " [witness_pruned: this acquire site was exercised "
                        "in the instrumented run and every acquisition was "
                        "released]"
                    )
            yield Finding(
                self.id, rec.module, rec.line, rec.col, message,
                witness_pruned=pruned,
            )

    @staticmethod
    def _witness_verdict(rec, witness) -> str:
        """"reproduced" | "pruned" | "unknown" against the runtime leak
        witness. Pruning is strict, like the lock witness: it needs the
        site to have actually been exercised — absence of evidence about
        an untouched path proves nothing."""
        sites = witness.get("sites", {})
        entry = sites.get(rec.site())
        if not entry:
            return "unknown"
        if int(entry.get("leaked", 0)) > 0:
            return "reproduced"
        if int(entry.get("acquired", 0)) > 0:
            return "pruned"
        return "unknown"


@register
class DoubleRelease(Rule):
    id = "LDT1202"
    name = "double-release"
    description = (
        "resource released again on a path where it may already be "
        "released (non-idempotent kinds: e.g. a shm token double-put "
        "hands one slot to two writers)"
    )
    family = "ownership"
    uses_owner_model = True

    def check_program(self, program, config) -> Iterable[Finding]:
        model = build_owner_model(program, config)
        for issue in model.issues:
            if issue.issue != "double-release":
                continue
            spec = model.spec(issue.kind)
            yield Finding(
                self.id, issue.module, issue.line, issue.col,
                f"{spec.describe or issue.kind} {issue.var!r} (acquired at "
                f"line {issue.acquire_line}) may already be released on "
                "this path — releasing twice hands the resource to two "
                "owners; release exactly once per exit path",
            )


@register
class UseAfterRelease(Rule):
    id = "LDT1203"
    name = "use-after-release"
    description = (
        "resource used on a path where it may already be released "
        "(shutdown-after-close, touching a recycled pool page)"
    )
    family = "ownership"
    uses_owner_model = True

    def check_program(self, program, config) -> Iterable[Finding]:
        model = build_owner_model(program, config)
        for issue in model.issues:
            if issue.issue != "use-after-release":
                continue
            spec = model.spec(issue.kind)
            yield Finding(
                self.id, issue.module, issue.line, issue.col,
                f"{spec.describe or issue.kind} {issue.var!r} (acquired at "
                f"line {issue.acquire_line}) may already be released here — "
                "the handle is no longer owned (a released page can be "
                "recycled under you; a closed socket raises); reorder the "
                "use before the release",
            )
