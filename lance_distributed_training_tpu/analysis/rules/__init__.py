"""Rule modules — importing this package registers every rule.

Adding a rule: create a module here (or extend one), subclass
:class:`~..core.Rule`, set ``id``/``name``/``description``, implement
``check_module`` (one file at a time) and/or ``check_project`` (cross-module
invariants), decorate with ``@register``, and import the module below. See
README "Static analysis" for a worked example.
"""

from . import (  # noqa: F401  (import for registration side effect)
    compat,
    concurrency,
    copies,
    determinism,
    dispatch,
    graph,
    jit_purity,
    lockorder,
    meshrules,
    obs,
    ownership,
    padding,
    persistence,
    placement,
    protocol,
    purity,
    resources,
    sharedstate,
    tunables,
    wireproto,
)
