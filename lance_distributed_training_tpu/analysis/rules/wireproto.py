"""Wire-protocol evolution rules (LDT1401-1404).

LDT501 pins the protocol *constants* and LDT1003 pins message-level
dispatch coverage; neither sees the payload *fields* — the level at which
mixed-version fleets actually rot. These rules consume the shared
:class:`~..protomodel.ProtoModel` (built once per ``ldt check`` run on top
of the same :class:`~..concmodel.ProgramInfo` every whole-program family
shares):

* **LDT1401 unchecked-payload-field** — a field some sender writes that no
  peer module ever reads or skew-checks (the forgotten-
  ``decode_config_skew`` class: add ``device_decode`` to the HELLO, forget
  the server-side check, and the knob silently stops mattering). Reported
  at the field's write site; reads inside the protocol module itself do
  not count — the schema owner validating its own dict proves nothing
  about the peer.
* **LDT1402 ungated-versioned-field** — a field the config declares
  version-gated (``[tool.ldt-check.protocol-versions]``: ``stripe_index =
  "STRIPE_MIN_VERSION"``) read or served in a function with no comparison
  against its gate constant anywhere on the caller chain — a v3-only
  feature consumed where a v1 peer can reach it.
* **LDT1403 orphan-decoded-field** — a field some receiver reads that no
  sender writes: dead drift (a removed field still consumed, a typo'd
  key, a reader merged before its writer). The runtime wire witness
  (``LDT_WIRE_SANITIZER=1`` + ``ldt check --wire-witness``) corroborates
  or prunes these exactly like the lock/leak witnesses: a (msg, field)
  tuple observed crossing the wire proves a writer the static model
  cannot see (``witness_pruned``); a message exercised without the field
  ever appearing upgrades the finding to *reproduced*.
* **LDT1404 out-of-module-framing** — raw ``struct.pack``/``unpack``/
  ``Struct`` byte-framing outside the protocol module (the LDT401/LDT801
  vocabulary shape): framing drift in two places is how two builds stop
  agreeing on a length prefix.

LDT14xx suppressions require a ``-- reason`` like the other whole-program
families (core's reason-required set covers LDT1[0-4]xx).
"""

from __future__ import annotations

from typing import Iterable

from ..core import Finding, ModuleInfo, Rule, register
from ..protomodel import build_proto_model

_STRUCT_CALLS = (
    "struct.pack", "struct.unpack", "struct.pack_into",
    "struct.unpack_from", "struct.Struct", "struct.iter_unpack",
)


@register
class UncheckedPayloadField(Rule):
    id = "LDT1401"
    name = "unchecked-payload-field"
    description = (
        "wire-payload field written by one peer but never read or "
        "skew-checked by the other (reads inside the protocol module "
        "do not count)"
    )
    family = "wire-protocol"
    uses_proto_model = True

    def check_program(self, program, config) -> Iterable[Finding]:
        model = build_proto_model(program, config)
        for site in model.orphan_writes():
            yield Finding(
                self.id, site.module, site.line, site.col,
                f"{site.msg} field {site.field!r} is written on the wire "
                "but no peer module reads or skew-checks it — either the "
                "receiving side forgot its check (the decode_config_skew "
                "class) or the field is dead; wire the read/skew check in "
                "or remove the field",
            )


@register
class UngatedVersionedField(Rule):
    id = "LDT1402"
    name = "ungated-versioned-field"
    description = (
        "version-gated payload field ([tool.ldt-check.protocol-versions]) "
        "read or served with no comparison against its gate constant on "
        "the path — a vN-only feature where an older peer can reach"
    )
    family = "wire-protocol"
    uses_proto_model = True

    def check_program(self, program, config) -> Iterable[Finding]:
        model = build_proto_model(program, config)
        if not model.messages:
            return  # protocol module not in this scan: family inert
        for gate in model.config_drift():
            yield Finding(
                self.id, model.proto_path, 1, 0,
                f"[tool.ldt-check.protocol-versions] names gate constant "
                f"{gate!r} which the protocol module does not define — "
                "config drift ahead of the protocol",
            )
        for field, gate, module, line, col, fn_key in model.ungated_sites:
            yield Finding(
                self.id, module, line, col,
                f"version-gated field {field!r} is used here, but neither "
                f"this function nor its callers compare the peer version "
                f"against {gate} — an old peer reaching this path gets a "
                "feature it does not speak (the silent-duplication / "
                "silent-ignore class); guard the path or refuse the peer",
            )


@register
class OrphanDecodedField(Rule):
    id = "LDT1403"
    name = "orphan-decoded-field"
    description = (
        "wire-payload field read by a receiver that no sender writes — "
        "dead-field drift (field-level extension of LDT1003's "
        "message-level dispatch coverage)"
    )
    family = "wire-protocol"
    uses_proto_model = True

    def check_program(self, program, config) -> Iterable[Finding]:
        model = build_proto_model(program, config)
        witness = getattr(config, "wire_witness", None)
        for site in model.orphan_reads():
            message = (
                f"{site.msg} field {site.field!r} is read here but no "
                "sender in the program writes it — dead drift (removed "
                "field still consumed, or a typo'd key); remove the read "
                "or restore the writer"
            )
            pruned = False
            if witness:
                verdict = model.witness_verdict(witness, site)
                if verdict == "pruned":
                    pruned = True
                    message += (
                        " [witness_pruned: this (msg, field) tuple was "
                        "observed crossing the wire in the instrumented "
                        "run — a writer exists outside the static model's "
                        "view]"
                    )
                elif verdict == "reproduced":
                    message += (
                        " [witness: the message was exercised on the wire "
                        "and this field never appeared — a reproduced "
                        "dead read, not an inference]"
                    )
            yield Finding(
                self.id, site.module, site.line, site.col, message,
                witness_pruned=pruned,
            )


@register
class OutOfModuleFraming(Rule):
    id = "LDT1404"
    name = "out-of-module-framing"
    description = (
        "raw struct.pack/unpack byte-framing outside the protocol module "
        "— wire framing must have exactly one owner"
    )
    family = "wire-protocol"

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        import ast

        if module.tree is None:
            return
        proto = getattr(config, "protocol_module", "")
        if module.relpath == proto:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            qn = module.qualname(node.func)
            if qn in _STRUCT_CALLS:
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    f"raw byte-framing ({qn}) outside the protocol module "
                    f"({proto or 'unset'}) — a second framing site is how "
                    "two builds stop agreeing on the wire; move the "
                    "pack/unpack behind the protocol module's encoders",
                )
