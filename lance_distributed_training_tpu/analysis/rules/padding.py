"""Padding-hygiene rule (LDT1501).

The ragged token plane (r15, ``data/token_pack.py``) exists because padding
token batches to a dataset-wide max length burned FLOPs and bandwidth
proportional to sequence-length variance. The cheapest way to reintroduce
that tax is one innocent-looking call on a hot path:

* ``np.pad(...)`` — materialises a padded copy of something that was
  already addressable ragged;
* a full-``max_len`` token allocation — ``np.zeros((B, seq_len))`` /
  ``np.full((n, max_len), pad_id)`` / ``np.empty((..., pad_to))`` built
  from a *max-length-shaped* name, i.e. a dense token grid sized to the
  worst case instead of the batch's actual content.

Scoped to the ``hot-paths`` modules from ``[tool.ldt-check]``, with ONE
exemption: ``data/token_pack.py`` itself — the padded control arm must
live somewhere, and keeping every full-length allocation in the module
that also measures its waste (``pack_grid_tokens_total``) is the point of
the rule. Everywhere else, ragged values+offsets (or the planner) is the
answer; a deliberate exception can still be grandfathered in the baseline
or carry a reasoned ``# ldt: ignore[LDT1501]``.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterable

from ..core import Finding, ModuleInfo, Rule, register

# Shape-name fragments that mean "sized to the maximum, not the content".
_MAX_SHAPE_NAMES = ("max_len", "seq_len", "pad_to", "max_length")

_ALLOCATORS = {"zeros", "full", "empty", "ones"}

# The padded control arm's home: exempt (see module docstring).
_EXEMPT = ("*token_pack.py",)


def _mentions_max_name(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is not None:
            folded = name.lower()
            if any(frag in folded for frag in _MAX_SHAPE_NAMES):
                return True
    return False


@register
class PaddingHygiene(Rule):
    id = "LDT1501"
    family = "padding"
    name = "padding-hygiene"
    description = (
        "hot-path modules: no np.pad and no full-max_len token-grid "
        "allocations (np.zeros/full/empty/ones shaped by a "
        "max_len/seq_len/pad_to name) outside data/token_pack.py — the "
        "ragged plane exists so padding waste is measured there, not "
        "silently reintroduced elsewhere"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        hot_paths = getattr(config, "hot_paths", [])
        if not any(fnmatch.fnmatch(module.relpath, p) for p in hot_paths):
            return
        if any(fnmatch.fnmatch(module.relpath, p) for p in _EXEMPT):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr == "pad":
                # np.pad / jnp.pad on a hot path: a padded copy of data
                # that was already addressable. (Method .pad on arbitrary
                # objects is rare enough on these modules that the
                # attribute name is the signal; baseline a deliberate one.)
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    ".pad() on a hot path materialises a padded copy — "
                    "carry the ragged values+offsets convention "
                    "(data/token_pack.py) instead, or move the padding "
                    "into token_pack.py where its waste is measured",
                )
                continue
            if func.attr in _ALLOCATORS and node.args:
                shape = node.args[0]
                if _mentions_max_name(shape):
                    yield Finding(
                        self.id, module.relpath, node.lineno,
                        node.col_offset,
                        f".{func.attr}(...) allocates a full-max-length "
                        "token grid (shape references a "
                        "max_len/seq_len/pad_to name) — dataset-max "
                        "padding belongs in token_pack.py's padded "
                        "control arm, where pack_grid_tokens_total "
                        "measures it",
                    )
