"""Cross-thread shared-state rule (LDT1002).

The reproducible-pipelines argument (PAPERS.md, arxiv 2604.21275): the
determinism contracts a distributed loader advertises die exactly at
unsynchronized cross-thread state — a cursor bumped by a receiver thread
and read by a checkpointing consumer, a lease dict swapped by a heartbeat
daemon under no lock. This rule consumes the shared
:class:`~..concmodel.ProgramInfo` and reports every ``self.<attr>`` that is
*written on one spawned-thread path and accessed on a different thread
path* with no common lock between the two sites.

What does NOT fire (the model's happens-before and handoff carve-outs):

* accesses in ``__init__`` — the object is not yet shared;
* writes that precede the first ``threading.Thread(...)`` statement of a
  spawning, main-rooted function (the ``start()`` publication pattern);
* attributes only ever assigned internally-synchronized values
  (``queue.Queue``, ``threading.Event``, ``collections.deque``, this
  repo's ``ServiceCounters``/``MetricsRegistry``, … — config
  ``threadsafe-types``) — using such an object IS the sanctioned handoff;
* any write/access pair the lock model proves share a lock (including
  locks held at every call site, the ``_locked`` convention).

A surviving finding is either a bug (add the lock, or route the value
through a queue/Event) or a *reviewed* benign race — suppress those with a
reasoned ignore; LDT10xx ignores without a ``-- reason`` stay live.
"""

from __future__ import annotations

from typing import Iterable

from ..core import Finding, Rule, register


@register
class UnsynchronizedSharedState(Rule):
    id = "LDT1002"
    name = "unsynchronized-shared-state"
    description = (
        "attribute written on a spawned-thread path and accessed on "
        "another thread path with no common lock or sanctioned handoff"
    )
    family = "shared-state"

    def check_program(self, program, config) -> Iterable[Finding]:
        for ckey, attr, w, a in program.attr_conflicts():
            cls_name = ckey.rsplit(".", 1)[-1]
            w_threads = program.describe_roots(w.func)
            if a is w:
                detail = (
                    f"the single write site runs on multiple threads "
                    f"({w_threads})"
                )
            else:
                a_threads = program.describe_roots(a.func)
                a_kind = "written" if a.write else (
                    "called through" if a.call_through else "read"
                )
                detail = (
                    f"written on {w_threads} and {a_kind} on {a_threads} "
                    f"at {a.module}:{a.line}"
                )
            yield Finding(
                self.id, w.module, w.line, w.col,
                f"unsynchronized shared state: {cls_name}.{attr} {detail} "
                "with no common lock — guard both sides with one lock, or "
                "hand the value off via a queue/Event (reviewed benign "
                "races need a reasoned `# ldt: ignore[LDT1002] -- why`)",
            )
