"""Dispatcher-exhaustiveness rule (LDT1003).

LDT501 pins the protocol *constants* (defined where referenced, values
consistent). It says nothing about *behavior*: add ``MSG_FLEET_DRAIN = 24``
to ``service/protocol.py``, teach the coordinator to send it, and every
LDT501 check stays green while the agent's dispatch loop silently falls
through to its error counter. This rule upgrades the contract to coverage:

* the config's ``dispatch`` table names each dispatcher module's inbound
  vocabulary (server: HELLO/ACK/ERROR; coordinator: the four fleet
  requests; …);
* every ``MSG_*`` constant the protocol module defines must appear in at
  least one dispatcher's vocabulary — a new frame type nobody is declared
  to handle is a finding at its definition line;
* every declared constant must be **behaviorally dispatched** in its
  module: compared against a received message type (``==``/``!=``/``in``)
  or keyed in a handler dict. Declaring is not handling — the reference
  must sit in dispatch position, so deleting the ``elif`` arm fails the
  gate even though the import still resolves. A comparison whose branch
  *rejects* the message counts: explicit rejection is a handled outcome.

The rule is inert when none of the configured dispatcher modules are in
the scanned set (fixture trees checking other rules), and a vocabulary
entry naming an undefined constant is itself a finding — the config must
never drift ahead of the protocol.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional, Set

from ..core import Finding, ModuleInfo, Rule, register


def _constant_defs(proto: ModuleInfo) -> Dict[str, int]:
    """MSG_* name → definition line in the protocol module."""
    out: Dict[str, int] = {}
    for node in proto.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        else:
            continue
        if isinstance(target, ast.Name) and target.id.startswith("MSG_"):
            out[target.id] = node.lineno
    return out


def _proto_const_ref(module: ModuleInfo, node: ast.AST,
                     proto_name: str) -> Optional[str]:
    """The MSG_* constant a Name/Attribute resolves to (through the import
    map), or None."""
    qn = module.qualname(node)
    if qn is None:
        return None
    if qn.startswith(proto_name + "."):
        leaf = qn[len(proto_name) + 1:]
        if "." not in leaf and leaf.startswith("MSG_"):
            return leaf
    return None


def _dispatched_constants(module: ModuleInfo, proto_name: str) -> Set[str]:
    """MSG_* constants this module dispatches on: referenced inside a
    comparison (against a received type) or as a handler-dict key."""
    out: Set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Compare):
            for sub in ast.walk(node):
                name = _proto_const_ref(module, sub, proto_name)
                if name:
                    out.add(name)
        elif isinstance(node, ast.Dict):
            for key in node.keys:
                if key is None:
                    continue
                name = _proto_const_ref(module, key, proto_name)
                if name:
                    out.add(name)
        elif isinstance(node, ast.Match):
            for case in node.cases:
                for sub in ast.walk(case.pattern):
                    name = _proto_const_ref(module, sub, proto_name)
                    if name:
                        out.add(name)
    return out


@register
class DispatcherExhaustiveness(Rule):
    id = "LDT1003"
    name = "dispatcher-exhaustiveness"
    description = (
        "protocol MSG_* constant with no dispatcher declared to handle "
        "it, or a dispatcher missing behavioral coverage (comparison / "
        "handler-dict key) for its declared vocabulary"
    )
    family = "dispatch"

    def check_project(self, modules, config) -> Iterable[Finding]:
        proto = next(
            (m for m in modules if m.relpath == config.protocol_module), None
        )
        if proto is None or proto.tree is None:
            return
        dispatch: Dict[str, list] = getattr(config, "dispatch", {}) or {}
        by_path = {m.relpath: m for m in modules}
        dispatchers = {
            path: by_path[path] for path in dispatch if path in by_path
        }
        if not dispatchers:
            return  # no configured dispatcher in this scan: nothing to gate
        defs = _constant_defs(proto)
        proto_name = proto.dotted_name
        declared: Set[str] = set()
        for path, vocabulary in sorted(dispatch.items()):
            declared.update(vocabulary)
            module = dispatchers.get(path)
            if module is None:
                continue
            covered = _dispatched_constants(module, proto_name)
            for const in sorted(set(vocabulary)):
                if const not in defs:
                    yield Finding(
                        self.id, path, 1, 0,
                        f"dispatch vocabulary names {const!r} which "
                        f"{config.protocol_module} does not define — "
                        "config drift ahead of the protocol",
                    )
                    continue
                if const not in covered:
                    yield Finding(
                        self.id, path, 1, 0,
                        f"dispatcher does not handle {const!r}: no "
                        "comparison or handler-dict entry dispatches it — "
                        "add the arm (or an explicit rejection) so the "
                        "frame type has a behavior, not a fall-through",
                    )
        for const, line in sorted(defs.items()):
            if const not in declared:
                yield Finding(
                    self.id, config.protocol_module, line, 0,
                    f"protocol constant {const!r} is in no dispatcher's "
                    "vocabulary ([tool.ldt-check.dispatch]) — a frame "
                    "type nobody is declared to handle; wire it into the "
                    "receiving dispatcher(s) and list it there",
                )
