"""Copy-hygiene rule (LDT701).

The r6 zero-copy batch plane exists because redundant materialisation
between pipeline stages — not decode math — capped loader throughput
(`PERF_NOTES_r05.md` §1). The cheapest way to reintroduce that tax is one
innocent-looking call on a hot path:

* ``col.to_pylist()`` — materialises a Python ``bytes`` object per row of
  an Arrow binary column (the reference's per-batch pattern this repo was
  built to kill; the native decoder reads the column's buffers directly);
* ``col.to_pybytes()`` — same, one giant copy instead of many;
* ``bytes(buf[...])`` / ``bytes(f(...))`` — copies a memoryview/buffer
  slice into a fresh ``bytes`` just to hand it to something that accepts a
  buffer.

Scoped to the ``hot-paths`` modules from ``[tool.ldt-check]`` (decode, the
pipelines, the worker/buffer planes, both halves of the service wire):
everywhere else a pylist is a perfectly fine debugging tool. Grandfathered
sites (deliberate fallbacks, tiny control-frame copies) live in the
baseline — new ones fail the gate.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterable

from ..core import Finding, ModuleInfo, Rule, register

_MATERIALIZERS = {"to_pylist", "to_pybytes"}


@register
class CopyHygiene(Rule):
    id = "LDT701"
    family = "copies"
    name = "copy-hygiene"
    description = (
        "hot-path modules: no .to_pylist()/.to_pybytes() on Arrow columns "
        "and no bytes(...) materialisation of buffer slices — the zero-copy "
        "plane exists to avoid exactly these"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        hot_paths = getattr(config, "hot_paths", [])
        if not any(fnmatch.fnmatch(module.relpath, p) for p in hot_paths):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MATERIALIZERS
            ):
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    f".{node.func.attr}() on a hot path materialises every "
                    "row as Python objects — feed the Arrow buffers to the "
                    "consumer directly (native decoder / numpy view), or "
                    "grandfather a deliberate fallback in the baseline",
                )
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id == "bytes"
                and len(node.args) == 1
                and not node.keywords
                and isinstance(node.args[0], (ast.Subscript, ast.Call))
            ):
                # bytes(view[a:b]) / bytes(f(...)): a full copy of a buffer
                # that was already addressable as a memoryview. bytes(name)
                # and bytes(<int>) stay legal — too many legitimate uses.
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    "bytes(...) over a subscript/call result copies a "
                    "buffer that is already addressable — pass the "
                    "memoryview through (or baseline a deliberate "
                    "small-control-frame copy)",
                )
