"""Crash-consistency rule (LDT901).

A state-persisting module (checkpoint cursors, lint baselines — anything a
*restart reads and trusts*) must never write its file in place: a SIGKILL
between ``open(path, "w")`` and the final flush leaves a torn document that
the next boot parses, half-applies, or dies on. The sanctioned pattern is
write-to-temp + ``os.replace`` (atomic on POSIX within a filesystem), as
``utils/checkpoint.py:atomic_write_json`` implements.

The rule flags truncating writes (``open(..., "w"/"wb"/"w+")`` and
``Path.write_text/write_bytes``) in modules matched by the ``state-paths``
config whose *enclosing function* never calls ``os.replace``/``os.rename``
— presence of the rename in the same function is taken as the tempfile
pattern (the temp file itself is then the thing being opened). Append-mode
opens are exempt: append-only JSONL logs lose at most the in-flight line,
which is a different durability contract than a document a restart trusts
wholesale.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterable

from ..core import Finding, ModuleInfo, Rule, register

_RENAMES = {"os.replace", "os.rename"}
_PATH_WRITERS = {"write_text", "write_bytes"}


def _write_mode(node: ast.Call) -> str:
    """The mode string of an ``open()`` call, '' when absent/dynamic."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"  # open() default
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return ""  # dynamic mode: give the benefit of the doubt


@register
class NonAtomicStateWrite(Rule):
    id = "LDT901"
    family = "persistence"
    name = "non-atomic-state-write"
    description = (
        "truncating file write in a state-persisting module without "
        "tempfile + os.replace — a crash mid-write leaves a torn file the "
        "restart then trusts"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        state_paths = getattr(config, "state_paths", [])
        if not any(
            fnmatch.fnmatch(module.relpath, pat) for pat in state_paths
        ):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            what = None
            qn = module.qualname(node.func)
            if qn in ("open", "builtins.open") or (
                isinstance(node.func, ast.Name) and node.func.id == "open"
            ):
                mode = _write_mode(node)
                if mode.startswith(("w", "x")):
                    what = f"open(..., {mode!r})"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _PATH_WRITERS
            ):
                what = f".{node.func.attr}(...)"
            if what is None:
                continue
            if self._atomic_in_scope(module, node):
                continue
            yield Finding(
                self.id, module.relpath, node.lineno, node.col_offset,
                f"{what} persists state in place — a crash mid-write "
                "leaves a torn file the restart trusts; write to a "
                "tempfile and os.replace() it into place "
                "(utils/checkpoint.py:atomic_write_json)",
            )

    @staticmethod
    def _atomic_in_scope(module: ModuleInfo, node: ast.AST) -> bool:
        """True when the enclosing function (or module, for top-level
        writes) also calls os.replace/os.rename — the write is then the
        tempfile half of the atomic pattern."""
        scope = module.enclosing(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        )
        if scope is None:
            scope = module.tree
        for n in ast.walk(scope):
            if isinstance(n, ast.Call):
                qn = module.qualname(n.func)
                if qn in _RENAMES:
                    return True
        return False
