"""Placement-hygiene rule (LDT801).

The r7 placement plane (``data/placement.py``) exists because every loader
used to end in a private ``jax.device_put`` on the consumer thread — the
step then waited on the H2D transfer instead of overlapping it (~97%
loader stall in BENCH_AB_r05). The cheapest way to reintroduce that stall
is one innocent ``jax.device_put(batch)`` in a hot-path module: it works,
it is synchronous, and nothing measures it separately.

This rule rejects direct calls to the H2D primitives — ``jax.device_put``
and ``make_array_from_single_device_arrays`` (however imported from jax) —
in the ``hot-paths`` modules from ``[tool.ldt-check]``, outside the two
modules allowed to own them: ``data/placement.py`` (the plane) and
``parallel/_compat.py`` (the version shim both primitives are re-exported
from). Calls routed through the shim (``from ..parallel._compat import
device_put``) resolve to the compat module's dotted name and are legal;
the import map distinguishes them from jax's, so no suppression comments
are needed for the sanctioned paths. Same baseline machinery as LDT701:
grandfather a deliberate site with ``ldt check --update-baseline`` or a
``# ldt: ignore[LDT801]``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, ModuleInfo, Rule, register

# jax-qualified names of the H2D primitives the placement plane owns.
# make_array_from_process_local_data is the synchronous multi-process
# assembly — the exact consumer-thread transfer the plane replaces — so
# it is fenced too (the plane's own fallback uses the _compat re-export).
_H2D_QUALNAMES = {
    "jax.device_put",
    "jax.make_array_from_single_device_arrays",
    "jax.experimental.array.make_array_from_single_device_arrays",
    "jax.make_array_from_process_local_data",
}

# Modules allowed to touch them directly (besides the compat shim, which
# comes from config so a repo relayout keeps working).
_PLACEMENT_MODULE_SUFFIX = "data/placement.py"


@register
class PlacementHygiene(Rule):
    id = "LDT801"
    family = "placement"
    name = "placement-hygiene"
    description = (
        "hot-path modules: no direct jax.device_put / "
        "make_array_from_single_device_arrays — H2D belongs to the "
        "placement plane (data/placement.py) or the _compat shim, so "
        "transfers stay async, measured (trainer_h2d_ms), and off the "
        "consumer thread"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        import fnmatch

        hot_paths = getattr(config, "hot_paths", [])
        if not any(fnmatch.fnmatch(module.relpath, p) for p in hot_paths):
            return
        if module.relpath.endswith(_PLACEMENT_MODULE_SUFFIX):
            return
        if module.relpath == getattr(config, "compat_module", ""):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            qn = module.qualname(node.func)
            if qn in _H2D_QUALNAMES:
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    f"direct {qn}(...) on a hot path runs the H2D transfer "
                    "synchronously on the calling thread, invisible to the "
                    "trainer_h2d_ms accounting — route it through the "
                    "placement plane (data/placement.py) or the _compat "
                    "re-export, or baseline a deliberate site",
                )
