"""Cross-module protocol-consistency rule (LDT501).

The wire protocol's frame-type and version constants live in ONE module
(``service/protocol.py``); the client and server reference them by
attribute. A constant referenced but not defined is a guaranteed
``AttributeError`` on a code path that may only fire mid-outage (error
frames, resume handshakes); a *redefined* constant with a different value is
worse — two peers silently speaking different dialects. This rule checks the
whole project at once:

* every uppercase attribute referenced on an alias of the protocol module
  must be defined there;
* any module-level constant elsewhere whose name collides with a protocol
  constant must carry the identical literal value.
"""

from __future__ import annotations

import ast
from typing import Iterable, Sequence

from ..core import Finding, ModuleInfo, Rule, register

_MISSING = object()


def _module_constants(module: ModuleInfo) -> dict:
    """Module-level UPPERCASE name → literal value (or _MISSING when the
    value is not a literal — presence still counts). Handles both plain
    assignments and annotated ones (``MSG_FOO: int = 7``)."""
    out = {}
    for node in module.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        else:
            continue
        if isinstance(target, ast.Name) and target.id.isupper():
            try:
                out[target.id] = ast.literal_eval(value)
            except (ValueError, SyntaxError):
                out[target.id] = _MISSING
    return out


@register
class ProtocolConsistency(Rule):
    id = "LDT501"
    family = "protocol"
    name = "protocol-consistency"
    description = (
        "frame-type/version constant referenced on the protocol module but "
        "not defined there, or redefined elsewhere with a different value"
    )

    def check_project(
        self, modules: Sequence[ModuleInfo], config
    ) -> Iterable[Finding]:
        proto = next(
            (m for m in modules if m.relpath == config.protocol_module), None
        )
        if proto is None:
            return
        defined = _module_constants(proto)
        proto_name = proto.dotted_name
        for module in modules:
            if module is proto:
                continue
            aliases = {
                alias
                for alias, target in module.imports.items()
                if target == proto_name
            }
            # (a) referenced-but-undefined: P.MSG_FOO with no MSG_FOO.
            for node in ast.walk(module.tree):
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in aliases
                    and node.attr.isupper()
                    and node.attr not in defined
                ):
                    yield Finding(
                        self.id, module.relpath,
                        node.lineno, node.col_offset,
                        f"protocol constant {node.attr!r} referenced via "
                        f"{node.value.id}.{node.attr} is not defined in "
                        f"{config.protocol_module} — AttributeError on "
                        "first use",
                    )
            # from-imports of specific constants.
            for alias, target in module.imports.items():
                if (
                    target.startswith(proto_name + ".")
                    and target.rsplit(".", 1)[1].isupper()
                    and target.rsplit(".", 1)[1] not in defined
                ):
                    yield Finding(
                        self.id, module.relpath, 1, 0,
                        f"from-import of protocol constant "
                        f"{target.rsplit('.', 1)[1]!r} which is not defined "
                        f"in {config.protocol_module}",
                    )
            # (b) redefinitions with mismatched values.
            local = _module_constants(module)
            for name, value in local.items():
                if name not in defined:
                    continue
                canonical = defined[name]
                if (
                    value is not _MISSING
                    and canonical is not _MISSING
                    and value != canonical
                ):
                    line = next(
                        (
                            n.lineno
                            for n in module.tree.body
                            if (
                                isinstance(n, ast.Assign)
                                and any(
                                    isinstance(t, ast.Name) and t.id == name
                                    for t in n.targets
                                )
                            )
                            or (
                                isinstance(n, ast.AnnAssign)
                                and isinstance(n.target, ast.Name)
                                and n.target.id == name
                            )
                        ),
                        1,
                    )
                    yield Finding(
                        self.id, module.relpath, line, 0,
                        f"protocol constant {name} redefined as {value!r} "
                        f"but {config.protocol_module} says {canonical!r} — "
                        "two peers would speak different dialects; import "
                        "it from the protocol module instead",
                    )
