"""Resource-leak rule (LDT301).

Leaked file handles and sockets are the slow killers of long training runs:
a service host accepting thousands of connections or a logger re-opened per
epoch eventually hits EMFILE mid-run. The rule demands that every acquired
handle has a *visible* ownership story, not a perfect escape analysis:

acquisitions (``open``, ``os.fdopen``, ``socket.socket``,
``socket.create_connection``, ``tarfile.open``, ``gzip.open``) are fine when

* used as a ``with`` context manager;
* returned (ownership transfers to the caller);
* passed whole into another call (ownership transfers to the callee, e.g. a
  session object or ``weakref.finalize``);
* a local that is ``.close()``/``.shutdown()``-ed somewhere in the same
  function;
* stored on ``self`` of a class that defines ``close``/``shutdown``/
  ``stop``/``__exit__``/``__del__`` — the instance owns it and has a
  teardown surface callers can reach.

Anything else — most importantly a bare-expression acquisition or a
``self.x = open(...)`` in a class with no teardown method — is flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..core import Finding, ModuleInfo, Rule, register

_ACQUIRE = {
    "open", "io.open", "os.fdopen", "tarfile.open", "gzip.open",
    "socket.socket", "socket.create_connection",
}
_CLOSE_METHODS = {"close", "shutdown", "stop", "__exit__", "__del__"}


@register
class UnclosedResource(Rule):
    id = "LDT301"
    family = "resources"
    name = "unclosed-resource"
    description = (
        "open()/socket result without a visible ownership story (with / "
        "close in function / returned / stored on a class with teardown)"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if module.qualname(node.func) not in _ACQUIRE:
                continue
            problem = self._ownership_gap(module, node)
            if problem:
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    problem,
                )

    def _ownership_gap(self, module: ModuleInfo, node: ast.Call):
        qn = module.qualname(node.func)
        # Inside a `with` item (directly or under an enclosing expression
        # like io.TextIOWrapper(open(...)))?
        cur: ast.AST = node
        parent = module.parents.get(cur)
        while parent is not None and not isinstance(parent, ast.stmt):
            if isinstance(parent, ast.withitem):
                return None
            if isinstance(parent, ast.Call) and parent is not node:
                return None  # wrapped/passed into another call: transferred
            cur = parent
            parent = module.parents.get(cur)
        # The climb above already handled `with` items (withitem parent) and
        # call-wrapping; `yield open(...)` falls through to the final
        # return None (an Expr statement whose value is the Yield, not the
        # acquisition itself).
        stmt = parent
        if isinstance(stmt, ast.Return):
            return None
        func = module.enclosing(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        )
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
            if isinstance(target, ast.Name):
                return self._check_local(module, node, func, target.id, qn)
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                return self._check_self_attr(module, node, qn)
            return None  # tuple targets etc.: out of scope
        if isinstance(stmt, ast.Expr) and stmt.value is node:
            return (
                f"{qn}() result discarded — the handle leaks immediately; "
                "use a with block or keep and close it"
            )
        return None

    def _check_local(self, module, node, func, name, qn):
        scope = func if func is not None else module.tree
        transferred = False
        for n in ast.walk(scope):
            if isinstance(n, ast.Call):
                attr = (
                    n.func.attr if isinstance(n.func, ast.Attribute) else None
                )
                owner = (
                    n.func.value
                    if isinstance(n.func, ast.Attribute)
                    else None
                )
                if (
                    attr in ("close", "shutdown")
                    and isinstance(owner, ast.Name)
                    and owner.id == name
                ):
                    return None
                # Passed whole as an argument: ownership transferred.
                if any(
                    isinstance(a, ast.Name) and a.id == name for a in n.args
                ):
                    transferred = True
            if isinstance(n, ast.Return) and (
                isinstance(n.value, ast.Name) and n.value.id == name
                or isinstance(n.value, ast.Tuple)
                and any(
                    isinstance(e, ast.Name) and e.id == name
                    for e in n.value.elts
                )
            ):
                return None
            # Re-assigned onto self: the self-attr rules take over.
            if isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and isinstance(n.value, ast.Name)
                and n.value.id == name
                for t in n.targets
            ):
                return self._check_self_attr(module, node, qn)
            if isinstance(n, ast.withitem) and (
                isinstance(n.context_expr, ast.Name)
                and n.context_expr.id == name
            ):
                return None
        if transferred:
            return None
        return (
            f"{qn}() assigned to {name!r} but never closed in this function "
            "(no close/shutdown, not returned, not handed off) — wrap in "
            "with, or close in a finally"
        )

    def _check_self_attr(self, module, node, qn):
        cls = module.enclosing(node, ast.ClassDef)
        if cls is None:
            return None  # self outside a class body: can't reason
        methods = {
            n.name
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if methods & _CLOSE_METHODS:
            return None
        return (
            f"{qn}() stored on self in class {cls.name!r}, which defines "
            f"none of {sorted(_CLOSE_METHODS)} — the handle outlives every "
            "scope with no teardown surface; add close() (and ideally "
            "__enter__/__exit__) and call it from shutdown"
        )
