"""Autotune-actuation rule (LDT1101).

An autotuner with an unbounded actuator is how a controller melts a host:
grow-on-stall against a saturated disk grows the worker pool forever, a
prefetch knob with no ceiling buffers the epoch in RAM. The runtime
``Tunable`` constructor requires ``lo``/``hi`` keywords, but that check
fires on the first *tick* of a running controller — this rule moves it to
commit time: every ``Tunable(...)`` construction site in the package must
declare both bounds, and literal bounds must form a non-degenerate range
(``lo < hi``; a degenerate range means the knob is not tunable and should
not be registered at all).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, ModuleInfo, Rule, register


def _literal_int(node) -> object:
    if isinstance(node, ast.Constant) and isinstance(
        node.value, (int, float)
    ) and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _literal_int(node.operand)
        return -inner if inner is not None else None
    return None


@register
class TunableBounds(Rule):
    id = "LDT1101"
    family = "tune"
    name = "tunable-bounds"
    description = (
        "a registered Tunable must declare lo=/hi= actuation bounds "
        "(unbounded actuation is how autotuners melt hosts)"
    )

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            qn = module.qualname(node.func) or ""
            if not (qn == "Tunable" or qn.endswith(".Tunable")):
                continue
            kwargs = {kw.arg: kw.value for kw in node.keywords
                      if kw.arg is not None}
            has_splat = any(kw.arg is None for kw in node.keywords)
            missing = sorted({"lo", "hi"} - set(kwargs))
            if missing:
                if has_splat:
                    # **kwargs may carry the bounds — benefit of the doubt
                    # (the runtime keyword-only check still backstops it).
                    continue
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    f"Tunable(...) without {'/'.join(missing)}= — every "
                    "registered knob needs explicit actuation bounds, or "
                    "the controller's grow-on-stall loop has no ceiling",
                )
                continue
            lo = _literal_int(kwargs["lo"])
            hi = _literal_int(kwargs["hi"])
            if lo is not None and hi is not None and lo >= hi:
                yield Finding(
                    self.id, module.relpath, node.lineno, node.col_offset,
                    f"Tunable(...) bounds [{lo}, {hi}] are degenerate "
                    "(lo >= hi) — a knob with no range is not tunable; "
                    "don't register it",
                )
