"""Static analysis for distributed-training invariants (``ldt check``).

An AST-based lint subsystem with project-specific rules: plan determinism
(LDT001-003), jit purity (LDT101-102), concurrency hygiene (LDT201-203),
resource ownership (LDT301), jax-compat enforcement (LDT401), cross-module
wire-protocol consistency (LDT501), and the whole-program concurrency
model (``concmodel.py``): lock-order deadlock cycles (LDT1001),
cross-thread unsynchronized shared state (LDT1002), dispatcher
exhaustiveness over the protocol's MSG_* vocabulary (LDT1003) — with a
runtime lock-order witness (``utils/lockorder.py`` +
``ldt check --lock-witness``) corroborating or pruning the static cycles,
and ``ldt graph --dot`` rendering the thread/lock topology. Configured
under ``[tool.ldt-check]`` in pyproject.toml; per-line suppression via
``# ldt: ignore[LDTxxx]`` (LDT10xx ignores require a ``-- reason``);
grandfathered findings live in a baseline file.

Programmatic surface::

    from lance_distributed_training_tpu.analysis import analyze, load_config
    findings = analyze(repo_root, load_config(repo_root))
"""

from .config import CheckConfig, load_config  # noqa: F401
from .core import (  # noqa: F401
    Finding,
    ModuleInfo,
    Rule,
    all_rules,
    analyze,
    analyze_project,
    register,
)
from .cli import check_main, graph_main  # noqa: F401
from .concmodel import ProgramInfo, build_program  # noqa: F401

__all__ = [
    "CheckConfig",
    "Finding",
    "ModuleInfo",
    "ProgramInfo",
    "Rule",
    "all_rules",
    "analyze",
    "analyze_project",
    "build_program",
    "check_main",
    "graph_main",
    "load_config",
    "register",
]
