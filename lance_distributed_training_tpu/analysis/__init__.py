"""Static analysis for distributed-training invariants (``ldt check``).

An AST-based lint subsystem with project-specific rules: plan determinism
(LDT001-003), jit purity (LDT101-102), concurrency hygiene (LDT201-203),
resource ownership (LDT301), jax-compat enforcement (LDT401), cross-module
wire-protocol consistency (LDT501), the whole-program concurrency model
(``concmodel.py``): lock-order deadlock cycles (LDT1001), cross-thread
unsynchronized shared state (LDT1002), dispatcher exhaustiveness over the
protocol's MSG_* vocabulary (LDT1003) — and, layered on the same
ProgramInfo without a second parse (``ownermodel.py``), the
ownership/lifecycle dataflow (LDT1201 leak-on-path, LDT1202
double-release, LDT1203 use-after-release over the
``[tool.ldt-check.resources]`` vocabulary) and the content-purity taint
rule (LDT1301 over ``[tool.ldt-check.content-paths]``). Two runtime
witnesses close the evidence loop: the lock-order sanitizer
(``utils/lockorder.py`` + ``ldt check --lock-witness``) and the
resource-lease sanitizer (``utils/leaktrack.py`` + ``ldt check
--leak-witness``), each corroborating or pruning its static family.
``ldt graph --dot`` renders the thread/lock topology, ``--ownership``
adds resource nodes and leak edges. Configured under ``[tool.ldt-check]``
in pyproject.toml; per-line suppression via ``# ldt: ignore[LDTxxx]``
(LDT10xx/12xx/13xx ignores require a ``-- reason``); grandfathered
findings live in a baseline file.

Programmatic surface::

    from lance_distributed_training_tpu.analysis import analyze, load_config
    findings = analyze(repo_root, load_config(repo_root))
"""

from .config import CheckConfig, load_config  # noqa: F401
from .core import (  # noqa: F401
    Finding,
    ModuleInfo,
    Rule,
    all_rules,
    analyze,
    analyze_project,
    register,
)
from .cli import check_main, graph_main  # noqa: F401
from .concmodel import ProgramInfo, build_program  # noqa: F401
from .ownermodel import OwnerModel, build_owner_model  # noqa: F401

__all__ = [
    "CheckConfig",
    "Finding",
    "ModuleInfo",
    "OwnerModel",
    "ProgramInfo",
    "Rule",
    "all_rules",
    "analyze",
    "analyze_project",
    "build_owner_model",
    "build_program",
    "check_main",
    "graph_main",
    "load_config",
    "register",
]
