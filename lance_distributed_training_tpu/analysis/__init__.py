"""Static analysis for distributed-training invariants (``ldt check``).

An AST-based lint subsystem with project-specific rules: plan determinism
(LDT001-003), jit purity (LDT101-102), concurrency hygiene (LDT201-203),
resource ownership (LDT301), jax-compat enforcement (LDT401), and
cross-module wire-protocol consistency (LDT501). Configured under
``[tool.ldt-check]`` in pyproject.toml; per-line suppression via
``# ldt: ignore[LDTxxx]``; grandfathered findings live in a baseline file.

Programmatic surface::

    from lance_distributed_training_tpu.analysis import analyze, load_config
    findings = analyze(repo_root, load_config(repo_root))
"""

from .config import CheckConfig, load_config  # noqa: F401
from .core import (  # noqa: F401
    Finding,
    ModuleInfo,
    Rule,
    all_rules,
    analyze,
    analyze_project,
    register,
)
from .cli import check_main  # noqa: F401

__all__ = [
    "CheckConfig",
    "Finding",
    "ModuleInfo",
    "Rule",
    "all_rules",
    "analyze",
    "analyze_project",
    "check_main",
    "load_config",
    "register",
]
