"""Whole-program concurrency model (the LDT1001-1003 engine).

The per-module rules in :mod:`.rules` see one :class:`~.core.ModuleInfo` at
a time, which is exactly the wrong granularity for the bug classes a
distributed data plane actually deadlocks on: the lock acquired in
``fleet/coordinator.py`` and the lock acquired in ``obs/registry.py`` only
form a cycle *together*, and the attribute written by a thread spawned in
``service/server.py`` is read by a thread spawned in ``obs/http.py``. This
module parses nothing itself — it consumes the already-parsed module list
one ``ldt check`` run produced — and derives, in one pass:

* a **function table** (:class:`FunctionInfo` keyed by dotted qualname,
  nested ``def``\\ s included) with resolved call edges (``self.m()``,
  local/imported names, attribute calls through annotated or
  constructor-assigned attributes, class instantiation → ``__init__``);
* the **thread model**: every ``threading.Thread(target=...)`` spawn site,
  its resolved target, and the set of spawn roots each function is
  reachable from (``roots``; empty = only ever on the caller's thread);
* the **lock model**: every lock object (``self._lock =
  threading.Lock()`` attributes, module-level locks) with its creation
  site(s), every ``with <lock>`` acquisition, the lock-order edge set
  (lock A held while lock B is acquired — directly nested or through a
  resolved call chain), and the always-held-at-entry set per function
  (the ``_locked``-suffix convention, computed instead of trusted:
  the intersection of locks held at every resolved call site);
* the **shared-state model**: per ``(class, attribute)``, every
  ``self.attr`` read/write with the thread roots and held locks at that
  statement — ``__init__`` bodies and pre-spawn publication in a spawning
  function excluded (both are happens-before the thread exists).

Everything here is stdlib-only (``ast``) — like :mod:`.core`, the gate must
run even when the training package itself fails to import. The model is
deliberately conservative where resolution fails: an unresolvable call
contributes no edges (no false cycles from guesses), an unresolvable
``target=`` spawns no root. The runtime witness (``utils/lockorder.py`` +
``ldt check --lock-witness``) closes the other half: statically-inferred
edges that never happen get pruned by evidence, real ones get a trace.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import ModuleInfo

__all__ = [
    "ProgramInfo",
    "FunctionInfo",
    "ClassInfo",
    "LockInfo",
    "AttrAccess",
    "LockOrderEdge",
    "build_program",
]

# Constructors whose instances are internally synchronized (or immutable
# handles) — a shared attribute holding one of these is a sanctioned
# cross-thread handoff, not a data race. Matched as a suffix of the
# import-resolved constructor qualname; extended via config
# ``threadsafe-types``.
DEFAULT_THREADSAFE_TYPES = (
    "threading.Event",
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
    "threading.Barrier",
    "threading.Thread",
    "threading.local",
    "queue.Queue",
    "queue.LifoQueue",
    "queue.PriorityQueue",
    "queue.SimpleQueue",
    "multiprocessing.Queue",
    "collections.deque",
    # This repo's internally-locked telemetry objects.
    "ServiceCounters",
    "MetricsRegistry",
    "StepTimer",
)

_LOCK_CTORS = {"threading.Lock", "threading.RLock"}


@dataclasses.dataclass(frozen=True)
class LockInfo:
    """One lock identity: a ``self.<attr>`` lock of a class, or a
    module-level lock. ``key`` is the stable id the graphs use; ``sites``
    are the ``path:line`` creation points (the join key the runtime
    witness maps back onto)."""

    key: str  # "pkg.mod.Class._lock" or "pkg.mod._LOCK"
    reentrant: bool
    sites: Tuple[str, ...]  # ("pkg/mod.py:107", ...)


@dataclasses.dataclass
class AttrAccess:
    """One ``self.<attr>`` read or write."""

    attr: str
    write: bool
    module: str  # relpath
    line: int
    col: int
    func: str  # FunctionInfo key
    locks: Set[str] = dataclasses.field(default_factory=set)
    # True when the access is a bare load that is immediately called
    # (``self.q.put(...)``) — a delegation, not a value read. Only used to
    # refine messages; the race logic treats it as a read.
    call_through: bool = False


@dataclasses.dataclass(frozen=True)
class LockOrderEdge:
    """Lock ``src`` held while lock ``dst`` is acquired."""

    src: str
    dst: str
    module: str
    line: int
    col: int
    via: str  # "nested with" or "call chain f -> g"


@dataclasses.dataclass
class FunctionInfo:
    key: str  # dotted qualname, nested defs as parent.<name>
    module: str  # relpath
    node: ast.AST
    owner: Optional[str] = None  # owning class key, when it takes self
    calls: List[tuple] = dataclasses.field(default_factory=list)
    # [(callee_key, call_node, frozenset(held_lock_keys))]
    acquires: List[tuple] = dataclasses.field(default_factory=list)
    # [(lock_key, with_node)]
    spawns: List[tuple] = dataclasses.field(default_factory=list)
    # [(target_key_or_None, call_node)]
    accesses: List[AttrAccess] = dataclasses.field(default_factory=list)
    # Computed by the fixpoints:
    roots: Set[str] = dataclasses.field(default_factory=set)
    held_at_entry: Set[str] = dataclasses.field(default_factory=set)
    acquires_transitive: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class ClassInfo:
    key: str  # dotted qualname
    module: str
    node: ast.ClassDef
    lock_attrs: Dict[str, LockInfo] = dataclasses.field(default_factory=dict)
    # attr -> resolved constructor qualnames assigned to it (for the
    # threadsafe-type exemption) — only simple `self.x = Ctor(...)` forms.
    attr_ctors: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    # attr -> class keys (resolved), for attribute-call resolution.
    attr_types: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)


class ProgramInfo:
    """The cross-module model. Build with :func:`build_program` (cached per
    ``ldt check`` run by :func:`.core.analyze_project`)."""

    def __init__(self, modules: Sequence[ModuleInfo], config):
        self.modules = [m for m in modules if m.tree is not None]
        self.by_relpath = {m.relpath: m for m in self.modules}
        self.threadsafe_types = tuple(
            getattr(config, "threadsafe_types", None)
            or DEFAULT_THREADSAFE_TYPES
        )
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.locks: Dict[str, LockInfo] = {}
        self.lock_edges: List[LockOrderEdge] = []
        self.spawn_sites: List[tuple] = []  # (target_key, module, node)
        self._class_by_bare: Dict[str, List[str]] = {}
        self._collect()
        self._resolve_bodies()
        self._fixpoint_roots()
        self._fixpoint_held()
        self._fixpoint_acquires()
        self._collect_lock_edges()
        self._finalize_access_locks()

    # -- pass 1: declarations ------------------------------------------------

    def _collect(self) -> None:
        """Walk every module once: register classes, functions (nested defs
        included), lock attributes / module-level locks, and attribute
        constructor/annotation types."""
        for mod in self.modules:
            dotted = mod.dotted_name
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._collect_class(mod, dotted, node)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._collect_function(mod, f"{dotted}.{node.name}",
                                           node, owner=None)
                elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                    # Module-level lock: `_LOCK = threading.Lock()`.
                    t, v = node.targets[0], node.value
                    if isinstance(t, ast.Name) and isinstance(v, ast.Call):
                        qn = mod.qualname(v.func)
                        if qn in _LOCK_CTORS:
                            key = f"{dotted}.{t.id}"
                            self.locks[key] = LockInfo(
                                key=key,
                                reentrant=qn.endswith("RLock"),
                                sites=(f"{mod.relpath}:{node.lineno}",),
                            )

    def _collect_class(self, mod: ModuleInfo, dotted: str,
                       node: ast.ClassDef) -> None:
        ckey = f"{dotted}.{node.name}"
        cls = ClassInfo(key=ckey, module=mod.relpath, node=node)
        self.classes[ckey] = cls
        self._class_by_bare.setdefault(node.name, []).append(ckey)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect_function(
                    mod, f"{ckey}.{item.name}", item, owner=ckey
                )
        # Lock attributes + attribute types, from every method body (locks
        # are conventionally created in __init__, but start() patterns
        # exist too).
        for item in ast.walk(node):
            if not (isinstance(item, ast.Assign) and len(item.targets) == 1):
                continue
            t, v = item.targets[0], item.value
            if not (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                continue
            if isinstance(v, ast.IfExp):
                # `self.x = arg if arg is not None else default()` — the
                # guard-or-default idiom; either branch types the attr.
                for branch in (v.body, v.orelse):
                    if isinstance(branch, ast.Call):
                        qn = mod.qualname(branch.func)
                        if qn and qn not in _LOCK_CTORS:
                            cls.attr_ctors.setdefault(t.attr, set()).add(qn)
            if isinstance(v, ast.Call):
                qn = mod.qualname(v.func)
                if qn in _LOCK_CTORS:
                    site = f"{mod.relpath}:{item.lineno}"
                    key = f"{ckey}.{t.attr}"
                    prev = cls.lock_attrs.get(t.attr)
                    sites = (prev.sites if prev else ()) + (site,)
                    info = LockInfo(
                        key=key, reentrant=qn.endswith("RLock"), sites=sites
                    )
                    cls.lock_attrs[t.attr] = info
                    self.locks[key] = info
                elif qn:
                    cls.attr_ctors.setdefault(t.attr, set()).add(qn)
        # Constructor-parameter annotations: `def __init__(self, loader:
        # "FleetLoader")` + `self.loader = loader` gives the attr a type.
        init = next(
            (
                i for i in node.body
                if isinstance(i, ast.FunctionDef) and i.name == "__init__"
            ),
            None,
        )
        if init is not None:
            ann = {}
            for arg in list(init.args.args) + list(init.args.kwonlyargs):
                if arg.annotation is not None:
                    ann[arg.arg] = self._annotation_name(arg.annotation)
            for item in ast.walk(init):
                if not (
                    isinstance(item, ast.Assign)
                    and len(item.targets) == 1
                    and isinstance(item.targets[0], ast.Attribute)
                    and isinstance(item.targets[0].value, ast.Name)
                    and item.targets[0].value.id == "self"
                ):
                    continue
                value = item.value
                names = []
                if isinstance(value, ast.Name):
                    names.append(value.id)
                elif isinstance(value, ast.IfExp):
                    # `self.registry = registry if registry is not None
                    # else default_registry()` — the annotated param names
                    # the type either way.
                    for branch in (value.body, value.orelse):
                        if isinstance(branch, ast.Name):
                            names.append(branch.id)
                for name in names:
                    if name in ann and ann[name]:
                        cls.attr_ctors.setdefault(
                            item.targets[0].attr, set()
                        ).add(ann[name])

    @staticmethod
    def _annotation_name(node: ast.AST) -> Optional[str]:
        """Bare class name out of an annotation: ``Foo``, ``"Foo"``,
        ``Optional["Foo"]`` → ``Foo``."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value.strip().strip('"').split("[")[0].split(".")[-1]
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Subscript):  # Optional[X] / list[X]
            return ProgramInfo._annotation_name(node.slice)
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    def _collect_function(self, mod: ModuleInfo, key: str, node,
                          owner: Optional[str]) -> None:
        self.functions[key] = FunctionInfo(
            key=key, module=mod.relpath, node=node, owner=owner
        )
        # Nested defs: the placement plane's `produce`, pipeline closures.
        # They share the enclosing method's `self`, so they keep the owner.
        for item in node.body:
            for sub in ast.walk(item):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and self._is_direct_nested(node, sub):
                    self._collect_function(
                        mod, f"{key}.<locals>.{sub.name}", sub, owner=owner
                    )

    @staticmethod
    def _is_direct_nested(outer, candidate) -> bool:
        """True when ``candidate`` is nested in ``outer`` with no function
        boundary in between (deeper nesting registers from its own parent's
        _collect_function walk)."""
        for item in ast.walk(outer):
            if item is candidate:
                continue
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and item is not outer:
                for sub in ast.walk(item):
                    if sub is candidate:
                        return False
        return True

    # -- pass 2: bodies ------------------------------------------------------

    def _resolve_bodies(self) -> None:
        for fn in list(self.functions.values()):
            self._resolve_body(fn)

    def _resolve_body(self, fn: FunctionInfo) -> None:
        mod = self.by_relpath[fn.module]
        cls = self.classes.get(fn.owner) if fn.owner else None
        # Local variable types from `name = ClassName(...)` in this body.
        local_types: Dict[str, str] = {}
        for node in self._walk_own(fn.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                ckey = self._resolve_class(mod, node.value.func)
                if ckey:
                    local_types[node.targets[0].id] = ckey
        held: List[str] = []
        self._visit_block(fn, mod, cls, local_types, fn.node.body, held)

    def _walk_own(self, node):
        """Walk a function body, NOT descending into nested defs (they are
        their own FunctionInfo)."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            cur = stack.pop()
            yield cur
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(cur))

    def _visit_block(self, fn, mod, cls, local_types, body, held) -> None:
        """Statement-ordered walk tracking the with-lock stack (``held``)."""
        for stmt in body:
            if isinstance(stmt, ast.With):
                # Items acquire LEFT TO RIGHT and each is held while the
                # next acquires — `with a, b:` is `with a: with b:` for
                # ordering purposes, so extend `held` per item, not after
                # the whole statement.
                acquired: List[str] = []
                for item in stmt.items:
                    self._visit_exprs_in(fn, mod, cls, local_types, [item],
                                         held)
                    lk = self._lock_ref(mod, cls, item.context_expr)
                    if lk is not None:
                        fn.acquires.append((lk, stmt))
                        acquired.append(lk)
                        held.append(lk)
                self._visit_block(fn, mod, cls, local_types, stmt.body, held)
                for _ in acquired:
                    held.pop()
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested def: analyzed as its own function
            elif isinstance(stmt, (ast.If, ast.While)):
                self._visit_exprs_in(fn, mod, cls, local_types, [stmt.test],
                                     held)
                self._visit_block(fn, mod, cls, local_types, stmt.body, held)
                self._visit_block(fn, mod, cls, local_types, stmt.orelse,
                                  held)
            elif isinstance(stmt, ast.For):
                self._visit_exprs_in(
                    fn, mod, cls, local_types, [stmt.target, stmt.iter], held
                )
                self._visit_block(fn, mod, cls, local_types, stmt.body, held)
                self._visit_block(fn, mod, cls, local_types, stmt.orelse,
                                  held)
            elif isinstance(stmt, ast.Try):
                self._visit_block(fn, mod, cls, local_types, stmt.body, held)
                for handler in stmt.handlers:
                    self._visit_block(fn, mod, cls, local_types,
                                      handler.body, held)
                self._visit_block(fn, mod, cls, local_types, stmt.orelse,
                                  held)
                self._visit_block(fn, mod, cls, local_types,
                                  stmt.finalbody, held)
            else:
                self._visit_exprs_in(fn, mod, cls, local_types, [stmt], held)

    def _visit_exprs_in(self, fn, mod, cls, local_types, nodes, held) -> None:
        snapshot = frozenset(held)
        for top in nodes:
            if top is None:
                continue
            stack = [top]
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(node, ast.Call):
                    self._record_call(fn, mod, cls, local_types, node,
                                      snapshot)
                elif isinstance(node, ast.Attribute):
                    self._record_attr(fn, mod, cls, node, snapshot)
                stack.extend(ast.iter_child_nodes(node))

    # -- reference resolution ------------------------------------------------

    def _resolve_class(self, mod: ModuleInfo, func_expr) -> Optional[str]:
        """Class key a call expression instantiates, or None."""
        qn = mod.qualname(func_expr)
        if qn is None:
            return None
        if qn in self.classes:
            return qn
        # `beta.Beta` resolved `beta` → pkg.beta, giving pkg.beta.Beta ✓;
        # `from .x import C` gives pkg.x.C directly ✓. Fall back to a
        # unique bare-name match (string annotations, re-exports).
        bare = qn.rsplit(".", 1)[-1]
        keys = self._class_by_bare.get(bare, [])
        if len(keys) == 1:
            return keys[0]
        return None

    def _class_by_name(self, name: Optional[str]) -> Optional[str]:
        if not name:
            return None
        keys = self._class_by_bare.get(name, [])
        return keys[0] if len(keys) == 1 else None

    def _lock_ref(self, mod, cls: Optional[ClassInfo], expr) -> Optional[str]:
        """Lock key a with-context expression names, or None."""
        # `with self._lock:`
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls is not None
            and expr.attr in cls.lock_attrs
        ):
            return cls.lock_attrs[expr.attr].key
        # `with _MODULE_LOCK:` (possibly imported).
        qn = mod.qualname(expr)
        if qn is not None:
            if qn in self.locks:
                return qn
            # Same-module bare name.
            candidate = f"{mod.dotted_name}.{qn}"
            if candidate in self.locks:
                return candidate
        # `with other.obj._lock:` — attribute chain whose base resolves to
        # a typed attr; only one level deep (`self.pool._lock`).
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Attribute)
            and isinstance(expr.value.value, ast.Name)
            and expr.value.value.id == "self"
            and cls is not None
        ):
            for tkey in self._attr_class_keys(cls, expr.value.attr):
                target = self.classes.get(tkey)
                if target and expr.attr in target.lock_attrs:
                    return target.lock_attrs[expr.attr].key
        return None

    def _attr_class_keys(self, cls: ClassInfo, attr: str) -> List[str]:
        """Program classes an attribute of ``cls`` may hold instances of."""
        out = []
        for qn in cls.attr_ctors.get(attr, ()):
            ckey = qn if qn in self.classes else self._class_by_name(
                qn.rsplit(".", 1)[-1]
            )
            if ckey:
                out.append(ckey)
        return out

    def _method_key(self, ckey: str, name: str) -> Optional[str]:
        key = f"{ckey}.{name}"
        return key if key in self.functions else None

    def _resolve_callee(self, fn, mod, cls, local_types,
                        func_expr) -> Optional[str]:
        """FunctionInfo key a call expression targets, or None."""
        # self.m(...)
        if (
            isinstance(func_expr, ast.Attribute)
            and isinstance(func_expr.value, ast.Name)
        ):
            base = func_expr.value.id
            if base == "self" and cls is not None:
                got = self._method_key(cls.key, func_expr.attr)
                if got:
                    return got
                # Through a typed attribute is handled below via qualname
                # failure; self.m unresolved ends here.
                return None
            # local var of known class: `session.run`
            if base in local_types:
                return self._method_key(local_types[base], func_expr.attr)
        # obj attr chain `self.loader._dial_member(...)`
        if (
            isinstance(func_expr, ast.Attribute)
            and isinstance(func_expr.value, ast.Attribute)
            and isinstance(func_expr.value.value, ast.Name)
            and func_expr.value.value.id == "self"
            and cls is not None
        ):
            for tkey in self._attr_class_keys(cls, func_expr.value.attr):
                got = self._method_key(tkey, func_expr.attr)
                if got:
                    return got
            return None
        qn = mod.qualname(func_expr)
        if qn is None:
            return None
        if qn in self.functions:
            return qn
        if qn in self.classes:  # instantiation
            return self._method_key(qn, "__init__") or None
        # Same-module bare name (module-level def or nested sibling).
        candidate = f"{mod.dotted_name}.{qn}"
        if candidate in self.functions:
            return candidate
        # Nested function referenced by bare name inside its parent.
        candidate = f"{fn.key}.<locals>.{qn}"
        if candidate in self.functions:
            return candidate
        ckey = self._resolve_class(mod, func_expr)
        if ckey:
            return self._method_key(ckey, "__init__")
        return None

    def _spawn_target(self, fn, mod, cls, local_types,
                      call: ast.Call) -> Optional[str]:
        for kw in call.keywords:
            if kw.arg == "target":
                return self._resolve_callee(fn, mod, cls, local_types,
                                            kw.value)
        return None

    # -- recorders -----------------------------------------------------------

    def _record_call(self, fn, mod, cls, local_types, node: ast.Call,
                     held: frozenset) -> None:
        qn = mod.qualname(node.func)
        if qn == "threading.Thread":
            target = self._spawn_target(fn, mod, cls, local_types, node)
            fn.spawns.append((target, node))
            self.spawn_sites.append((target, fn.module, node))
            return
        callee = self._resolve_callee(fn, mod, cls, local_types, node.func)
        if callee is not None:
            fn.calls.append((callee, node, held))

    def _record_attr(self, fn, mod, cls, node: ast.Attribute,
                     held: frozenset) -> None:
        if not (isinstance(node.value, ast.Name) and node.value.id == "self"):
            return
        if cls is None:
            return
        if node.attr in cls.lock_attrs:
            return  # lock handles are the synchronization, not the state
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        parent_is_call = False
        if not write:
            parent = mod.parents.get(node)
            parent_is_call = (
                isinstance(parent, ast.Call) and parent.func is node
            )
        fn.accesses.append(
            AttrAccess(
                attr=node.attr,
                write=write,
                module=fn.module,
                line=node.lineno,
                col=node.col_offset,
                func=fn.key,
                locks=set(held),
                call_through=parent_is_call,
            )
        )

    # -- fixpoints -----------------------------------------------------------

    def _callers(self) -> Dict[str, List[tuple]]:
        callers: Dict[str, List[tuple]] = {}
        for fn in self.functions.values():
            for callee, node, held in fn.calls:
                callers.setdefault(callee, []).append((fn.key, held))
        return callers

    def _fixpoint_roots(self) -> None:
        """roots(f) = spawn targets f is reachable from (BFS per target)."""
        for target, _module, _node in self.spawn_sites:
            if target is None or target not in self.functions:
                continue
            seen = {target}
            stack = [target]
            while stack:
                cur = stack.pop()
                self.functions[cur].roots.add(target)
                for callee, _n, _h in self.functions[cur].calls:
                    if callee not in seen and callee in self.functions:
                        seen.add(callee)
                        stack.append(callee)

    def _fixpoint_held(self) -> None:
        """held_at_entry(f) = ∩ over resolved call sites of (site-held ∪
        caller's own entry set). Functions with no resolved callers hold
        nothing at entry. Decreasing fixpoint from ⊤."""
        callers = self._callers()
        TOP = None  # lattice top: "unconstrained"
        state: Dict[str, Optional[frozenset]] = {
            k: (frozenset() if k not in callers else TOP)
            for k in self.functions
        }
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for key, fn in self.functions.items():
                sites = callers.get(key)
                if not sites:
                    continue
                acc: Optional[frozenset] = TOP
                for caller_key, held in sites:
                    caller_entry = state.get(caller_key)
                    site_set = frozenset(held) | (
                        caller_entry if caller_entry else frozenset()
                    )
                    acc = site_set if acc is TOP else (acc & site_set)
                if acc is TOP:
                    acc = frozenset()
                if state[key] != acc:
                    state[key] = acc
                    changed = True
        for key, fn in self.functions.items():
            entry = state.get(key)
            fn.held_at_entry = set(entry or ())

    def _fixpoint_acquires(self) -> None:
        """acquires_transitive(f) = direct with-locks ∪ callees'. Increasing
        fixpoint (cycles in the call graph converge)."""
        for fn in self.functions.values():
            fn.acquires_transitive = {lk for lk, _n in fn.acquires}
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for fn in self.functions.values():
                for callee, _n, _h in fn.calls:
                    sub = self.functions.get(callee)
                    if sub is None:
                        continue
                    before = len(fn.acquires_transitive)
                    fn.acquires_transitive |= sub.acquires_transitive
                    if len(fn.acquires_transitive) != before:
                        changed = True

    # -- lock-order edges ----------------------------------------------------

    def _collect_lock_edges(self) -> None:
        """Edge src→dst for every acquisition of dst while src is held:
        a directly nested ``with``, a resolved call (at any depth) that
        acquires dst, or an acquisition in a function entered with src
        already held (held_at_entry)."""
        seen: Set[tuple] = set()

        def add(src, dst, module, node, via):
            lk = self.locks.get(src)
            if src == dst and lk is not None and lk.reentrant:
                return  # RLock re-entry is legal
            key = (src, dst, module, node.lineno, via)
            if key in seen:
                return
            seen.add(key)
            self.lock_edges.append(
                LockOrderEdge(
                    src=src, dst=dst, module=module, line=node.lineno,
                    col=getattr(node, "col_offset", 0), via=via,
                )
            )

        for fn in self.functions.values():
            # Direct acquisitions with something already held at entry
            # (the computed `_locked`-convention coverage).
            for lk, node in fn.acquires:
                for held in fn.held_at_entry:
                    add(held, lk, fn.module, node,
                        f"acquired in {fn.key} (entered holding)")
            self._edges_in_function(fn, add)

    def _edges_in_function(self, fn: FunctionInfo, add) -> None:
        """Re-walk the function's statements with the with-stack to catch
        nested-with and call-under-lock edges (the body walk in pass 2
        kept call-site held-sets, which is what we need here)."""
        # Nested with: acquires list order does not carry nesting, so use
        # the recorded call held-sets plus a dedicated nested-with scan.
        mod = self.by_relpath[fn.module]
        cls = self.classes.get(fn.owner) if fn.owner else None

        def scan(body, held):
            for stmt in body:
                if isinstance(stmt, ast.With):
                    # `with a, b:` == `with a: with b:` — item N is held
                    # while item N+1 acquires, so the edge records per
                    # item, against everything held so far INCLUDING
                    # earlier items of this same statement.
                    acquired = []
                    for item in stmt.items:
                        lk = self._lock_ref(mod, cls, item.context_expr)
                        if lk is not None:
                            for h in held:
                                add(h, lk, fn.module, stmt, "nested with")
                            acquired.append(lk)
                            held.append(lk)
                    scan(stmt.body, held)
                    for _ in acquired:
                        held.pop()
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                elif isinstance(stmt, (ast.If, ast.While, ast.For)):
                    scan(stmt.body, held)
                    scan(stmt.orelse, held)
                elif isinstance(stmt, ast.Try):
                    scan(stmt.body, held)
                    for h_ in stmt.handlers:
                        scan(h_.body, held)
                    scan(stmt.orelse, held)
                    scan(stmt.finalbody, held)

        scan(fn.node.body, [])
        # Calls made while holding locks (at the site or since entry),
        # whose callees transitively acquire more.
        for callee, node, held in fn.calls:
            effective = set(held) | fn.held_at_entry
            if not effective:
                continue
            sub = self.functions.get(callee)
            if sub is None:
                continue
            for dst in sub.acquires_transitive:
                for src in effective:
                    add(src, dst, fn.module, node,
                        f"call chain {fn.key} -> {callee}")

    def _finalize_access_locks(self) -> None:
        """Fold each function's entry-held locks into its accesses (the
        ``_locked``-convention half of the lock coverage)."""
        for fn in self.functions.values():
            if not fn.held_at_entry:
                continue
            for acc in fn.accesses:
                acc.locks |= fn.held_at_entry

    # -- queries the rules use ----------------------------------------------

    def lock_cycles(self) -> List[List[LockOrderEdge]]:
        """Elementary cycles in the lock-order graph, as edge lists.
        Deduplicated by the cycle's lock set; self-loops (non-reentrant
        re-acquisition) come out as single-edge cycles."""
        adj: Dict[str, List[LockOrderEdge]] = {}
        for e in self.lock_edges:
            adj.setdefault(e.src, []).append(e)
        cycles: List[List[LockOrderEdge]] = []
        seen_sets: Set[frozenset] = set()

        for e in self.lock_edges:
            if e.src == e.dst:
                key = frozenset([e.src])
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append([e])

        def dfs(start: str, cur: str, path: List[LockOrderEdge],
                on_path: Set[str]) -> None:
            for edge in adj.get(cur, ()):
                if edge.dst == edge.src:
                    continue
                if edge.dst == start and path:
                    key = frozenset(x.src for x in path + [edge])
                    if key not in seen_sets:
                        seen_sets.add(key)
                        cycles.append(path + [edge])
                    continue
                if edge.dst in on_path:
                    continue
                # Canonical start: only explore nodes >= start so each
                # cycle is found from its smallest lock key exactly once.
                if edge.dst < start:
                    continue
                on_path.add(edge.dst)
                dfs(start, edge.dst, path + [edge], on_path)
                on_path.discard(edge.dst)

        for node in sorted(adj):
            dfs(node, node, [], {node})
        return cycles

    def attr_conflicts(self) -> List[tuple]:
        """Cross-thread unsynchronized (class, attr) conflicts:
        ``(class_key, attr, write_access, other_access)`` — one per
        conflicting WRITE site (so a reviewed suppression on one write
        never hides a different racy write of the same attr), paired with
        the first access it can race against. Exemptions: accesses in
        ``__init__``; pre-spawn publication (writes before the first
        ``Thread(...)`` statement of a spawning, unrooted function); attrs
        only ever assigned threadsafe-constructor values outside
        ``__init__`` — and, of course, any pair sharing a lock."""
        by_attr: Dict[tuple, List[AttrAccess]] = {}
        for fn in self.functions.values():
            if fn.owner is None:
                continue
            init_key = f"{fn.owner}.__init__"
            in_init = fn.key == init_key or fn.key.startswith(
                init_key + ".<locals>."
            )
            if in_init:
                continue
            first_spawn = min(
                (n.lineno for _t, n in fn.spawns), default=None
            )
            for acc in fn.accesses:
                if (
                    not fn.roots
                    and first_spawn is not None
                    and acc.line <= first_spawn
                ):
                    # start()-pattern publication: the access precedes the
                    # spawn that makes the attr visible to another thread —
                    # ordinary happens-before, not a race (applies to the
                    # pre-spawn reads too: nothing else exists yet).
                    continue
                by_attr.setdefault((fn.owner, acc.attr), []).append(acc)

        conflicts = []
        for (ckey, attr), accesses in sorted(by_attr.items()):
            writes = [a for a in accesses if a.write]
            if not writes:
                continue
            if self._attr_is_threadsafe(ckey, attr, writes):
                continue
            for w, a in self._conflicting_pairs(writes, accesses):
                conflicts.append((ckey, attr, w, a))
        return conflicts

    def _attr_is_threadsafe(self, ckey: str, attr: str,
                            writes: List[AttrAccess]) -> bool:
        cls = self.classes.get(ckey)
        if cls is None:
            return False
        ctors = cls.attr_ctors.get(attr)
        if not ctors:
            return False
        return all(
            any(qn.endswith(suffix) for suffix in self.threadsafe_types)
            for qn in ctors
        )

    def _roots_of(self, acc: AttrAccess) -> frozenset:
        fn = self.functions.get(acc.func)
        roots = fn.roots if fn is not None else set()
        return frozenset(roots) if roots else frozenset(["<main>"])

    def _conflicting_pairs(self, writes, accesses) -> List[tuple]:
        """For each write site, the first access it can race against (or
        itself, when the one site is reachable from two thread roots)."""
        ordered = sorted(
            accesses, key=lambda a: (a.module, a.line, a.col, not a.write)
        )
        out = []
        for w in sorted(writes, key=lambda a: (a.module, a.line, a.col)):
            w_roots = self._roots_of(w)
            for a in ordered:
                if a is w:
                    # A single site reachable from two different thread
                    # roots races with itself.
                    if len(w_roots) < 2 or w.locks:
                        continue
                    out.append((w, w))
                    break
                a_roots = self._roots_of(a)
                combined = w_roots | a_roots
                if len(combined) < 2:
                    continue  # always the same single thread
                if w.locks & a.locks:
                    continue  # a common lock serializes them
                out.append((w, a))
                break
        return out

    # -- presentation helpers ------------------------------------------------

    def describe_roots(self, fn_key: str) -> str:
        fn = self.functions.get(fn_key)
        if fn is None or not fn.roots:
            return "<main>"
        return "+".join(sorted(r.rsplit(".", 2)[-2] + "." +
                               r.rsplit(".", 1)[-1] if "." in r else r
                               for r in fn.roots))

    def lock_display(self, key: str) -> str:
        parts = key.split(".")
        return ".".join(parts[-2:]) if len(parts) >= 2 else key


def build_program(modules: Sequence[ModuleInfo], config) -> ProgramInfo:
    """Build the model once per run (``analyze_project`` memoizes on the
    module list identity so the three LDT10xx rules share one pass)."""
    return ProgramInfo(modules, config)
