"""Analyzer core: module model, rule registry, suppressions, baseline.

The distributed-training bug classes this subsystem gates — nondeterministic
plan construction, host syncs inside jitted step functions, leaked workers,
unbounded queues — all share a property: they pass every fast test and then
silently corrupt a scaling run days later. A lint pass makes them visible at
commit time instead. The design mirrors the pluggable-rule linters (flake8,
ruff) at a fraction of the machinery:

* :class:`ModuleInfo` — one parsed source file: AST with parent links, an
  import-alias map (``np`` → ``numpy``), raw lines, and per-line suppression
  state (``# ldt: ignore[LDT001]``).
* :class:`Rule` — subclasses register with :func:`register`; a rule checks
  either one module at a time (``check_module``) or the whole project at once
  (``check_project`` — cross-module invariants like protocol-constant
  consistency).
* :class:`Finding` — one violation, with a line-content fingerprint so the
  baseline survives line drift.
* Baseline — grandfathered findings stored in a JSON file; ``ldt check``
  fails only on findings NOT in the baseline, so the gate can be adopted on
  an imperfect codebase and ratcheted down.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import hashlib
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = [
    "Finding",
    "ModuleInfo",
    "Rule",
    "register",
    "all_rules",
    "analyze",
    "analyze_project",
    "parse_modules",
    "load_baseline",
    "write_baseline",
    "fingerprint",
    "split_new_findings",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a specific source location.

    ``witness_pruned`` is set (never by hand — by the LDT1001 witness
    cross-check) when runtime lock-order evidence contradicts the static
    inference: the finding still renders (flagged) but does not fail the
    gate and never enters a baseline.
    """

    rule: str  # "LDT001"
    path: str  # root-relative posix path
    line: int  # 1-based
    col: int  # 0-based
    message: str
    witness_pruned: bool = False

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}"


_SUPPRESS_RE = re.compile(
    r"#\s*ldt:\s*ignore(?:\[(?P<rules>[A-Za-z0-9_,\s]+)\])?"
    r"(?:\s*(?:--|—)\s*(?P<reason>\S.*))?"
)

# The cross-module rules: their findings assert whole-program properties
# (a deadlock cycle, a cross-thread race, a leak-on-path, taint into a
# content computation, a payload field one peer forgot), so an unexplained
# per-line ignore is exactly the "trust me" a reviewer cannot review.
# Suppressions for the concurrency (LDT10xx), ownership (LDT12xx), purity
# (LDT13xx), wire-protocol (LDT14xx), and device-semantics (LDT17xx)
# families require a reason string:
#     # ldt: ignore[LDT1002] -- GIL-atomic monotonic cursor, torn reads ok
_REASON_REQUIRED_RE = re.compile(r"LDT1[02347]\d\d$")


def _parse_suppressions(lines: Sequence[str]) -> Dict[int, tuple]:
    """Per-line suppressions: line number → ``(rules, reason)`` where
    ``rules`` is a set of rule ids or ``None`` meaning "every rule" (bare
    ``# ldt: ignore``), and ``reason`` is the free text after ``--`` (or
    ``None`` when absent — LDT10xx rules refuse reasonless ignores)."""
    out: Dict[int, tuple] = {}
    for i, text in enumerate(lines, start=1):
        if "ldt:" not in text:  # cheap pre-filter
            continue
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = m.group("rules")
        reason = m.group("reason")
        if rules is not None:
            rules = {r.strip().upper() for r in rules.split(",") if r.strip()}
        out[i] = (rules, reason.strip() if reason else None)
    return out


class ModuleInfo:
    """A parsed source file plus the derived maps every rule needs."""

    def __init__(self, root: str, relpath: str, source: str):
        self.root = root
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=relpath)
        except SyntaxError as exc:
            self.syntax_error = exc
        self.suppressions = _parse_suppressions(self.lines)
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.imports: Dict[str, str] = {}
        if self.tree is not None:
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self.parents[child] = parent
            self._collect_imports()

    # -- identity ----------------------------------------------------------

    @property
    def dotted_name(self) -> str:
        """``pkg/sub/mod.py`` → ``pkg.sub.mod`` (``__init__`` → ``pkg.sub``)."""
        mod = self.relpath[:-3] if self.relpath.endswith(".py") else self.relpath
        parts = mod.split("/")
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    @property
    def package(self) -> str:
        """The package a level-1 relative import resolves against: for an
        ``__init__.py`` that is the package itself (its dotted name), for a
        regular module it is the parent."""
        if self.relpath.endswith("__init__.py"):
            return self.dotted_name
        return (
            self.dotted_name.rsplit(".", 1)[0]
            if "." in self.dotted_name else ""
        )

    def _collect_imports(self) -> None:
        """Alias → absolute dotted module/symbol map. Relative imports are
        resolved against this module's package so cross-module rules can
        match ``from . import protocol as P`` to the real protocol file."""
        assert self.tree is not None
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:  # `import numpy.random as npr`
                        self.imports[alias.asname] = alias.name
                    else:  # `import numpy.random` binds the top name only
                        top = alias.name.split(".")[0]
                        self.imports[top] = top
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: climb from this module's package
                    pkg_parts = self.package.split(".") if self.package else []
                    climb = node.level - 1
                    if climb:
                        pkg_parts = pkg_parts[: -climb or None]
                    base = ".".join(pkg_parts + ([base] if base else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.imports[alias.asname or alias.name] = (
                        f"{base}.{alias.name}" if base else alias.name
                    )

    # -- helpers for rules -------------------------------------------------

    def qualname(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with the leading alias
        resolved through the import map: ``np.random.shuffle`` →
        ``numpy.random.shuffle``. ``None`` for non-name expressions."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        parts[0] = self.imports.get(parts[0], parts[0])
        return ".".join(parts)

    def enclosing(self, node: ast.AST, kinds) -> Optional[ast.AST]:
        """Nearest ancestor of one of ``kinds`` (a class or tuple of AST
        node classes), or None."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = self.parents.get(cur)
        return None

    def statement_of(self, node: ast.AST) -> ast.AST:
        """The innermost statement containing ``node``."""
        cur = node
        while not isinstance(cur, ast.stmt):
            parent = self.parents.get(cur)
            if parent is None:
                return cur
            cur = parent
        return cur

    def suppressed(self, finding: Finding) -> bool:
        entry = self.suppressions.get(finding.line)
        if entry is None:
            return False
        rules, reason = entry
        if rules is not None and finding.rule not in rules:
            return False
        if _REASON_REQUIRED_RE.match(finding.rule) and not reason:
            # A bare ignore on an LDT10xx finding is ineffective by design:
            # the finding stays live, so the lint fails until the ignore
            # carries a `-- reason`.
            return False
        return True

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


class Rule:
    """Base class. Subclass, set ``id``/``name``/``description`` (and
    ``family`` — the ``rule_family`` the JSON reporter emits), implement
    ``check_module``, ``check_project``, and/or ``check_program``, decorate
    with ``@register``."""

    id: str = ""
    name: str = ""
    description: str = ""
    family: str = "general"

    def check_module(self, module: ModuleInfo, config) -> Iterable[Finding]:
        return ()

    def check_project(
        self, modules: Sequence[ModuleInfo], config
    ) -> Iterable[Finding]:
        return ()

    def check_program(self, program, config) -> Iterable[Finding]:
        """Cross-module rules over the shared concurrency model
        (:class:`~.concmodel.ProgramInfo`) — built ONCE per run and handed
        to every rule that overrides this, instead of each rule re-walking
        every AST."""
        return ()


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the global rule registry."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id}")
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> Dict[str, Rule]:
    # Importing the rules package populates the registry exactly once.
    from . import rules  # noqa: F401

    return dict(_REGISTRY)


# -- analysis driver -------------------------------------------------------


def _iter_py_files(root: str, paths: Sequence[str], exclude: Sequence[str]):
    """Yield root-relative posix paths of .py files under ``paths``."""
    seen = set()
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full) and full.endswith(".py"):
            candidates = [p]
        elif os.path.isdir(full):
            candidates = []
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git")
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, fn), root
                        )
                        candidates.append(rel.replace(os.sep, "/"))
        else:
            continue
        for rel in candidates:
            rel = rel.replace(os.sep, "/")
            if rel in seen:
                continue
            if any(fnmatch.fnmatch(rel, pat) for pat in exclude):
                continue
            seen.add(rel)
            yield rel


def analyze(root: str, config) -> List[Finding]:
    """Parse every configured file and run every enabled rule.

    Returns findings sorted by (path, line, rule), with per-line
    ``# ldt: ignore`` suppressions already applied. Files that fail to parse
    produce an LDT000 finding (an unparseable file cannot be checked, which
    is itself a gate failure) and are skipped by the rules.
    """
    return analyze_project(root, config)[0]


# Parse cache: (root, relpath, mtime_ns, size) → ModuleInfo. One `ldt
# check` run parses each file exactly once already; this carries the
# parses ACROSS runs in the same process (the test suite runs the
# full-repo analysis half a dozen times; the CLI pays one stat per file on
# a warm cache). ModuleInfo is never mutated after construction, so
# sharing is safe. Root and relpath are part of the key deliberately: a
# ModuleInfo's identity (its reported path, its dotted name, every
# relpath-keyed config match) depends on the root it was loaded under —
# the same file analyzed from a different root must be a different entry.
_MODULE_CACHE: Dict[tuple, ModuleInfo] = {}
_MODULE_CACHE_MAX = 1024


def _load_module(root: str, rel: str) -> ModuleInfo:
    full = os.path.join(root, rel)
    try:
        st = os.stat(full)
        key = (os.path.abspath(root), rel, st.st_mtime_ns, st.st_size)
    except OSError:
        key = None
    if key is not None:
        cached = _MODULE_CACHE.get(key)
        if cached is not None:
            return cached
    with open(full, encoding="utf-8") as f:
        source = f.read()
    mod = ModuleInfo(root, rel, source)
    if key is not None:
        if len(_MODULE_CACHE) >= _MODULE_CACHE_MAX:
            _MODULE_CACHE.clear()
        _MODULE_CACHE[key] = mod
    return mod


def parse_modules(root: str, config):
    """Parse (or cache-hit) every configured file WITHOUT running rules —
    ``(modules, findings, files_checked)`` where findings are the LDT000
    parse failures. ``ldt graph`` uses this directly: it needs the module
    set for the concurrency model, not a lint pass."""
    modules: List[ModuleInfo] = []
    findings: List[Finding] = []
    files_checked = 0
    for rel in _iter_py_files(root, config.paths, config.exclude):
        files_checked += 1
        try:
            mod = _load_module(root, rel)
        except OSError as exc:
            findings.append(Finding("LDT000", rel, 1, 0, f"unreadable: {exc}"))
            continue
        if mod.syntax_error is not None:
            findings.append(
                Finding(
                    "LDT000", rel, mod.syntax_error.lineno or 1, 0,
                    f"syntax error: {mod.syntax_error.msg}",
                )
            )
            continue
        modules.append(mod)
    return modules, findings, files_checked


def analyze_project(root: str, config, timing: Optional[dict] = None):
    """:func:`analyze` plus the parsed modules and total file count —
    ``(findings, modules, files_checked)``. The CLI uses the extras for
    reporting (line text, counts) without re-reading anything. ``timing``
    (a dict, filled in place) receives ``wall_ms`` / ``parse_ms`` for the
    ``--json`` report."""
    import time as _time

    t_start = _time.perf_counter()
    modules, findings, files_checked = parse_modules(root, config)
    t_parsed = _time.perf_counter()

    rules = {
        rid: rule for rid, rule in all_rules().items()
        if rid not in config.disable
    }
    by_path = {m.relpath: m for m in modules}
    # The cross-module models are built at most ONCE per run and shared by
    # every program-level rule: ProgramInfo (LDT1001-1003) and, layered on
    # top of it without re-walking any AST, the ownership/purity model
    # (LDT1201-1203, LDT1301). Per-family build time is recorded so the
    # --json report can prove the single-pass contract holds.
    program = None
    if any(
        type(rule).check_program is not Rule.check_program
        for rule in rules.values()
    ):
        from .concmodel import build_program

        t0 = _time.perf_counter()
        program = build_program(modules, config)
        t1 = _time.perf_counter()
        model_ms = {"concurrency": round((t1 - t0) * 1e3, 3)}
        if any(
            getattr(rule, "uses_proto_model", False)
            for rule in rules.values()
        ):
            from .protomodel import build_proto_model

            tp = _time.perf_counter()
            proto = build_proto_model(program, config)
            model_ms["protocol"] = round(
                (_time.perf_counter() - tp) * 1e3, 3
            )
            wire = getattr(config, "wire_witness", None)
            if wire is not None and timing is not None:
                # The corroboration receipt the CI wire-witness stage
                # asserts on: how much of the runtime (msg, field)
                # evidence maps onto the static schema.
                timing["wire_witness"] = proto.witness_receipt(wire)
        if any(
            getattr(rule, "uses_owner_model", False)
            for rule in rules.values()
        ):
            from .ownermodel import build_owner_model

            t_own = _time.perf_counter()
            owner = build_owner_model(program, config)
            model_ms["ownership"] = round(
                (_time.perf_counter() - t_own) * 1e3, 3
            )
            witness = getattr(config, "leak_witness", None)
            if witness is not None and timing is not None:
                # The corroboration receipt the CI leak-witness stage
                # asserts on: how much of the runtime evidence maps onto
                # static acquire sites the model knows.
                static_sites = owner.acquire_sites()
                wsites = witness.get("sites", {})
                timing["leak_witness"] = {
                    "runtime_sites": len(wsites),
                    "matched_sites": sum(
                        1 for s in wsites if s in static_sites
                    ),
                    "leaked_sites": sum(
                        1 for v in wsites.values()
                        if int(v.get("leaked", 0)) > 0
                    ),
                }
        if any(
            getattr(rule, "uses_mesh_model", False)
            for rule in rules.values()
        ):
            from .meshmodel import build_mesh_model

            t_mesh = _time.perf_counter()
            mesh = build_mesh_model(program, config)
            model_ms["mesh"] = round(
                (_time.perf_counter() - t_mesh) * 1e3, 3
            )
            compile_w = getattr(config, "compile_witness", None)
            if compile_w is not None and timing is not None:
                # The corroboration receipt the CI compile-witness stage
                # asserts on: how much of the runtime compile/transfer
                # evidence maps onto static jit sites.
                timing["compile_witness"] = mesh.witness_receipt(compile_w)
        if timing is not None:
            timing["model_build_ms"] = model_ms
    for rule in rules.values():
        for mod in modules:
            findings.extend(rule.check_module(mod, config))
        findings.extend(rule.check_project(modules, config))
        if program is not None and (
            type(rule).check_program is not Rule.check_program
        ):
            findings.extend(rule.check_program(program, config))

    kept = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    if timing is not None:
        t_end = _time.perf_counter()
        timing["parse_ms"] = round((t_parsed - t_start) * 1e3, 3)
        timing["wall_ms"] = round((t_end - t_start) * 1e3, 3)
    return kept, modules, files_checked


# -- baseline --------------------------------------------------------------


def fingerprint(finding: Finding, line_text: str) -> str:
    """Stable id for a baseline entry: rule + path + normalized line content
    (NOT the line number, so pure line drift never un-grandfathers a
    finding). Two identical violations on identical lines in one file
    collapse to one fingerprint — acceptable: fixing one of them still
    leaves the fingerprint live, and fixing both retires it."""
    h = hashlib.sha256(
        f"{finding.rule}|{finding.path}|{' '.join(line_text.split())}".encode()
    )
    return h.hexdigest()[:16]


def _fingerprints(findings: Sequence[Finding], by_path) -> List[str]:
    out = []
    for f in findings:
        mod = by_path.get(f.path)
        text = mod.line_text(f.line) if mod is not None else ""
        out.append(fingerprint(f, text))
    return out


def load_baseline(path: str) -> set:
    """Fingerprint set from a baseline file; empty when absent."""
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {entry["fingerprint"] for entry in data.get("findings", [])}


def write_baseline(
    path: str,
    findings: Sequence[Finding],
    root: str,
    modules: Optional[Sequence[ModuleInfo]] = None,
) -> None:
    """Grandfather the current findings: future runs fail only on new ones.
    ``modules`` (from :func:`analyze_project`) supplies line text without
    re-reading files; disk is the fallback for paths not in it."""
    by_path = {m.relpath: m for m in (modules or ())}
    entries = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None:
            text = mod.line_text(f.line)
        else:
            try:
                with open(os.path.join(root, f.path),
                          encoding="utf-8") as fh:
                    lines = fh.read().splitlines()
                text = lines[f.line - 1] if 1 <= f.line <= len(lines) else ""
            except OSError:
                text = ""
        entries.append(
            {
                "fingerprint": fingerprint(f, text),
                "rule": f.rule,
                "path": f.path,
                "message": f.message,
            }
        )
    # Atomic replace (the LDT901 discipline): the baseline is state every
    # later `ldt check` trusts — a crash mid-write must leave the previous
    # baseline, not a torn JSON that fails the gate everywhere. Deliberate
    # duplication of utils/checkpoint.py:atomic_write_json: this module
    # must stay stdlib-only (the gate runs standalone even when the
    # training package — and its jax import — fails to load).
    import tempfile

    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(os.path.abspath(path)), prefix=".tmp-baseline-"
    )
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "findings": entries}, f, indent=2)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def split_new_findings(
    findings: Sequence[Finding],
    baseline: set,
    root: str,
    modules: Optional[Sequence[ModuleInfo]] = None,
) -> tuple:
    """(new, grandfathered) relative to a baseline fingerprint set.
    ``modules`` (from :func:`analyze_project`) supplies line text without
    re-reading files; disk is the fallback for paths not in it (LDT000)."""
    new, old = [], []
    cache: Dict[str, List[str]] = {
        m.relpath: m.lines for m in (modules or ())
    }
    for f in findings:
        if f.path not in cache:
            try:
                with open(os.path.join(root, f.path), encoding="utf-8") as fh:
                    cache[f.path] = fh.read().splitlines()
            except OSError:
                cache[f.path] = []
        lines = cache[f.path]
        text = lines[f.line - 1] if 1 <= f.line <= len(lines) else ""
        (old if fingerprint(f, text) in baseline else new).append(f)
    return new, old
