"""``[tool.ldt-check]`` configuration.

Loaded from the repo's ``pyproject.toml`` (stdlib ``tomllib`` on 3.11+,
``tomli`` as the 3.10 fallback the container ships). Every knob has a
default tuned to THIS repo, so ``ldt check`` with no config still gates the
package correctly; the pyproject section exists to disable rules, exclude
paths, and move the baseline.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

__all__ = ["CheckConfig", "load_config"]


@dataclasses.dataclass
class CheckConfig:
    """Knobs for the analyzer. Paths are root-relative posix."""

    # What to scan.
    paths: List[str] = dataclasses.field(
        default_factory=lambda: ["lance_distributed_training_tpu"]
    )
    exclude: List[str] = dataclasses.field(default_factory=list)  # fnmatch
    disable: List[str] = dataclasses.field(default_factory=list)  # rule ids
    # Baseline of grandfathered findings (``ldt check --update-baseline``).
    baseline: str = ".ldt-baseline.json"
    # LDT401: the one module allowed to import version-moved jax symbols.
    compat_module: str = "lance_distributed_training_tpu/parallel/_compat.py"
    compat_symbols: List[str] = dataclasses.field(
        default_factory=lambda: ["shard_map", "pcast", "axis_size"]
    )
    # LDT202: where an unbounded queue.Queue() is an error (streaming paths
    # whose backpressure contract depends on bounded queues).
    queue_paths: List[str] = dataclasses.field(
        default_factory=lambda: [
            "lance_distributed_training_tpu/service/*",
            "lance_distributed_training_tpu/data/pipeline.py",
            "lance_distributed_training_tpu/data/workers.py",
        ]
    )
    # LDT501: the protocol-constant source of truth. Also the one module
    # allowed to own raw byte-framing (LDT1404) and the schema owner whose
    # internal reads never satisfy the peer-read contract (LDT1401).
    protocol_module: str = "lance_distributed_training_tpu/service/protocol.py"
    # LDT1402: version-gated payload fields — "MSG_X.field" (or a bare
    # field name, gating it in every message) -> gate constant in the
    # protocol module. Any read (or keyword-serve into a schema
    # constructor) of the field outside the protocol module must sit in a
    # function — or under callers — comparing against that constant. TOML:
    # a ``[tool.ldt-check.protocol-versions]`` table.
    protocol_versions: Dict[str, str] = dataclasses.field(
        default_factory=lambda: {
            "MSG_HELLO.stripe_index": "STRIPE_MIN_VERSION",
            "MSG_HELLO.stripe_count": "STRIPE_MIN_VERSION",
        }
    )
    # LDT14xx: messages whose payloads are raw binary (framed tensors),
    # not JSON field dicts — excluded from field-schema tracking.
    protocol_binary: List[str] = dataclasses.field(
        default_factory=lambda: ["MSG_BATCH"]
    )
    # LDT1403 runtime witness (``ldt check --wire-witness``): set by the
    # CLI, never from TOML — {"frames": {msg_value: count}, "fields":
    # {msg_value: {field: count}}} recorded by utils/wiretrack.py under
    # LDT_WIRE_SANITIZER=1.
    wire_witness: Optional[dict] = None
    # LDT601: the instrumented modules (telemetry clock + metric-name
    # hygiene) — no time.time(); metric names must be Prometheus-safe.
    obs_paths: List[str] = dataclasses.field(
        default_factory=lambda: [
            "lance_distributed_training_tpu/obs/*",
            "lance_distributed_training_tpu/utils/metrics.py",
            "lance_distributed_training_tpu/utils/profiling.py",
            "lance_distributed_training_tpu/service/*",
            "lance_distributed_training_tpu/data/pipeline.py",
            "lance_distributed_training_tpu/data/workers.py",
            "lance_distributed_training_tpu/data/buffers.py",
        ]
    )
    # LDT901: state-persisting modules — files a RESTART reads and trusts
    # (checkpoint cursors, lint baselines). Truncating in-place writes here
    # must use tempfile + os.replace.
    state_paths: List[str] = dataclasses.field(
        default_factory=lambda: [
            "lance_distributed_training_tpu/utils/checkpoint.py",
            "lance_distributed_training_tpu/analysis/core.py",
        ]
    )
    # LDT1003: dispatcher exhaustiveness — each dispatcher module's inbound
    # message vocabulary. Every ``MSG_*`` constant in the protocol module
    # must appear in at least one entry, and each listed constant must be
    # behaviorally dispatched (compared against a received message type, or
    # keyed in a handler dict) in that module. TOML: a
    # ``[tool.ldt-check.dispatch]`` table of module-path → constant list.
    dispatch: Dict[str, List[str]] = dataclasses.field(
        default_factory=lambda: {
            "lance_distributed_training_tpu/service/server.py": [
                "MSG_HELLO", "MSG_ACK", "MSG_ERROR",
            ],
            "lance_distributed_training_tpu/service/client.py": [
                "MSG_HELLO_OK", "MSG_BATCH", "MSG_END", "MSG_ERROR",
            ],
            "lance_distributed_training_tpu/fleet/balancer.py": [
                "MSG_HELLO_OK", "MSG_BATCH", "MSG_END", "MSG_ERROR",
                "MSG_FLEET_RESOLVE_OK",
            ],
            "lance_distributed_training_tpu/fleet/coordinator.py": [
                "MSG_FLEET_REGISTER", "MSG_FLEET_HEARTBEAT",
                "MSG_FLEET_DEREGISTER", "MSG_FLEET_RESOLVE",
            ],
            "lance_distributed_training_tpu/fleet/agent.py": [
                "MSG_FLEET_REGISTER_OK", "MSG_FLEET_HEARTBEAT_OK",
                "MSG_FLEET_DEREGISTER_OK", "MSG_ERROR",
            ],
        }
    )
    # LDT1002: constructors whose instances are internally synchronized —
    # a shared attribute holding one is a sanctioned handoff, not a race.
    # Matched as suffixes of the import-resolved constructor qualname;
    # empty list = the built-in default set (concmodel module).
    threadsafe_types: List[str] = dataclasses.field(default_factory=list)
    # LDT1001 runtime witness (``ldt check --lock-witness``): set by the
    # CLI, never from TOML — {"edges": {(src, dst), ...},
    # "acquired": {site: count}} with root-relative "path:line" sites.
    lock_witness: Optional[dict] = None
    # LDT12xx resource vocabulary: kind -> {acquire: [patterns],
    # release: [method names], describe, idempotent}. Acquire patterns
    # match the resolved callee's dotted tail (case/underscore-folded, so
    # ``BufferPool.lease`` also matches ``self.buffer_pool.lease``).
    # Empty dict = the built-in vocabulary (ownermodel.DEFAULT_RESOURCES:
    # pool-page, shm-token, socket, thread, autotuner). TOML: a
    # ``[tool.ldt-check.resources.<kind>]`` table per kind.
    resources: Dict[str, dict] = dataclasses.field(default_factory=dict)
    # LDT1301 content paths: the computations whose outputs must be pure
    # functions of (dataset, plan, seed, epoch, cursor) — plan generation,
    # batch assembly, cursor arithmetic, lineage digests. Entries are
    # ``path-glob[::function-glob]`` (function globs match dotted
    # qualnames). Taint sources found in these functions, or in functions
    # they reach through resolved calls within content modules, are
    # findings.
    content_paths: List[str] = dataclasses.field(
        default_factory=lambda: [
            "lance_distributed_training_tpu/data/samplers.py",
            "lance_distributed_training_tpu/data/decode.py",
            "lance_distributed_training_tpu/utils/chaos.py::*.batch_digest",
            "lance_distributed_training_tpu/*::*.state_dict",
            "lance_distributed_training_tpu/*::*.load_state_dict",
        ]
    )
    # Extra LDT1301 taint sources appended to the built-in set
    # (ownermodel.DEFAULT_TAINT_SOURCES): dotted call qualnames, or bare
    # names matched against the call's function/attribute name.
    taint_sources: List[str] = dataclasses.field(default_factory=list)
    # LDT1201 runtime witness (``ldt check --leak-witness``): set by the
    # CLI, never from TOML — {"sites": {"path:line": {"acquired": n,
    # "released": n, "leaked": n}}} with root-relative sites.
    leak_witness: Optional[dict] = None
    # LDT1701: the declared mesh-axis vocabulary — every literal axis name
    # in a PartitionSpec or collective must come from this list. Seeded
    # from parallel/mesh.py's get_mesh (data, model, seq, pipe). TOML:
    # ``mesh-axes``.
    mesh_axes: List[str] = dataclasses.field(
        default_factory=lambda: ["data", "model", "seq", "pipe"]
    )
    # LDT1703: the quantized funnels — call-name globs (matched against the
    # callee's dotted tail) through which a .shape/len()-derived value may
    # legitimately reach a jit static position, because the funnel clamps
    # it to a short ladder (coeff_chunk actuation, pack_rows_quantum
    # rounding). TOML: ``static-funnels``.
    static_funnels: List[str] = dataclasses.field(
        default_factory=lambda: [
            "coeff_chunk", "pack_rows_quantum", "rows_multiple",
            "*_quantum", "*_bucket",
        ]
    )
    # LDT1704: function-name globs (bare name or dotted-qualname tail)
    # allowed to host-sync deliberately — declared D2H doors. TOML:
    # ``sync-funnels``.
    sync_funnels: List[str] = dataclasses.field(default_factory=list)
    # LDT1704: the compute-plane hot modules where a stray host sync
    # serialises the dispatch stream (hot_paths above is the DATA plane's
    # copy discipline — different contract, different module set). TOML:
    # ``device-hot-paths``.
    device_hot_paths: List[str] = dataclasses.field(
        default_factory=lambda: [
            "lance_distributed_training_tpu/trainer.py",
            "lance_distributed_training_tpu/ops/*",
            "lance_distributed_training_tpu/parallel/*",
        ]
    )
    # LDT1703 runtime witness (``ldt check --compile-witness``): set by the
    # CLI, never from TOML — {"compiles": {"path:line": {"calls": n,
    # "distinct": k, "post_warmup": m}}, "transfers": {...}} recorded by
    # utils/compiletrack.py under LDT_COMPILE_SANITIZER=1.
    compile_witness: Optional[dict] = None
    # LDT701: the hot-path modules where materialising copies
    # (.to_pylist(), bytes(view[...])) undo the zero-copy batch plane.
    hot_paths: List[str] = dataclasses.field(
        default_factory=lambda: [
            "lance_distributed_training_tpu/data/decode.py",
            "lance_distributed_training_tpu/data/pipeline.py",
            "lance_distributed_training_tpu/data/workers.py",
            "lance_distributed_training_tpu/data/buffers.py",
            "lance_distributed_training_tpu/data/folder.py",
            "lance_distributed_training_tpu/native/jpeg.py",
            "lance_distributed_training_tpu/service/protocol.py",
            "lance_distributed_training_tpu/service/server.py",
            "lance_distributed_training_tpu/service/client.py",
        ]
    )


def _read_toml(path: str) -> Optional[dict]:
    try:
        import tomllib  # Python 3.11+
    except ImportError:
        try:
            import tomli as tomllib  # type: ignore[no-redef]
        except ImportError:
            return None
    try:
        with open(path, "rb") as f:
            return tomllib.load(f)
    except (OSError, ValueError):
        return None


def load_config(root: str) -> CheckConfig:
    """Defaults overlaid with ``[tool.ldt-check]`` from ``root/pyproject.toml``
    when present and parseable; silently falls back to defaults otherwise
    (no TOML parser must never break the gate)."""
    config = CheckConfig()
    data = _read_toml(os.path.join(root, "pyproject.toml"))
    if not data:
        return config
    section = data.get("tool", {}).get("ldt-check", {})
    mapping = {
        "paths": "paths",
        "exclude": "exclude",
        "disable": "disable",
        "baseline": "baseline",
        "compat-module": "compat_module",
        "compat-symbols": "compat_symbols",
        "queue-paths": "queue_paths",
        "protocol-module": "protocol_module",
        "protocol-versions": "protocol_versions",
        "protocol-binary": "protocol_binary",
        "obs-paths": "obs_paths",
        "hot-paths": "hot_paths",
        "state-paths": "state_paths",
        "dispatch": "dispatch",
        "threadsafe-types": "threadsafe_types",
        "resources": "resources",
        "content-paths": "content_paths",
        "taint-sources": "taint_sources",
        "mesh-axes": "mesh_axes",
        "static-funnels": "static_funnels",
        "sync-funnels": "sync_funnels",
        "device-hot-paths": "device_hot_paths",
    }
    for key, attr in mapping.items():
        if key in section:
            setattr(config, attr, section[key])
    config.disable = [r.upper() for r in config.disable]
    return config
