"""Finding reporters: human text and machine ``--json``."""

from __future__ import annotations

import json
from typing import Optional, Sequence, TextIO

from .core import Finding, fingerprint

__all__ = ["render_text", "render_json"]


def render_text(
    findings: Sequence[Finding],
    out: TextIO,
    *,
    grandfathered: int = 0,
    files_checked: int = 0,
) -> None:
    """flake8-style one-line-per-finding in stable (path, line, rule) order,
    followed by a summary line the gate scripts can grep."""
    for f in findings:
        out.write(f"{f.location()}: {f.rule} {f.message}\n")
    if findings:
        out.write(
            f"\nldt check: {len(findings)} new finding"
            f"{'s' if len(findings) != 1 else ''}"
        )
    else:
        out.write("ldt check: clean")
    if grandfathered:
        out.write(f" ({grandfathered} baselined)")
    out.write(f" [{files_checked} files]\n")


def render_json(
    findings: Sequence[Finding],
    out: TextIO,
    *,
    root: str,
    grandfathered: int = 0,
    files_checked: int = 0,
    line_text_of=None,
) -> None:
    """Machine output. Schema (stable — tests pin it)::

        {
          "version": 1,
          "clean": bool,
          "files_checked": int,
          "grandfathered": int,
          "findings": [
            {"rule", "path", "line", "col", "message", "fingerprint"}, ...
          ]
        }
    """
    records = []
    for f in findings:
        text = line_text_of(f) if line_text_of is not None else ""
        records.append(
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "message": f.message,
                "fingerprint": fingerprint(f, text),
            }
        )
    json.dump(
        {
            "version": 1,
            "clean": not findings,
            "files_checked": files_checked,
            "grandfathered": grandfathered,
            "findings": records,
        },
        out,
        indent=2,
    )
    out.write("\n")
