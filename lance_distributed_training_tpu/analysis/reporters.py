"""Finding reporters: human text and machine ``--json``."""

from __future__ import annotations

import json
from typing import Optional, Sequence, TextIO

from .core import Finding, fingerprint

__all__ = ["render_text", "render_json"]


def render_text(
    findings: Sequence[Finding],
    out: TextIO,
    *,
    grandfathered: int = 0,
    files_checked: int = 0,
) -> None:
    """flake8-style one-line-per-finding in stable (path, line, rule) order,
    followed by a summary line the gate scripts can grep. Witness-pruned
    findings (runtime evidence contradicts the static inference) render
    flagged and do not count toward the failing total."""
    failing = 0
    pruned = 0
    for f in findings:
        tag = ""
        if f.witness_pruned:
            pruned += 1
            tag = " [witness-pruned]"
        else:
            failing += 1
        out.write(f"{f.location()}: {f.rule} {f.message}{tag}\n")
    if failing:
        out.write(
            f"\nldt check: {failing} new finding"
            f"{'s' if failing != 1 else ''}"
        )
    else:
        out.write("ldt check: clean")
    if pruned:
        out.write(f" ({pruned} witness-pruned)")
    if grandfathered:
        out.write(f" ({grandfathered} baselined)")
    out.write(f" [{files_checked} files]\n")


def render_json(
    findings: Sequence[Finding],
    out: TextIO,
    *,
    root: str,
    grandfathered: int = 0,
    files_checked: int = 0,
    line_text_of=None,
    family_of=None,
    timing: Optional[dict] = None,
) -> None:
    """Machine output. Schema (stable — tests pin it)::

        {
          "version": 2,
          "clean": bool,             # no UNPRUNED new findings
          "files_checked": int,
          "grandfathered": int,
          "wall_time_ms": float,     # whole analysis pass (parse + rules)
          "parse_ms": float,
          "findings": [
            {"rule", "rule_family", "path", "line", "col", "message",
             "fingerprint", "witness_pruned"}, ...
          ]
        }

    v1 → v2: per-finding ``rule_family`` (the rule's family slug, e.g.
    ``lock-order``) and ``witness_pruned`` (true when the runtime lock
    witness contradicted the static inference — rendered, not failing),
    plus the top-level timing fields. Exit-code and baseline semantics are
    unchanged, so existing gate machinery keeps working unmodified.

    Additive v2 fields (r11): ``model_build_ms`` — per-family build time
    of the shared cross-module models ({"concurrency": ms, "ownership":
    ms, "protocol": ms}), the receipt that one ProgramInfo/parse pass
    serves every whole-program family — and ``leak_witness`` (only when
    ``ldt check --leak-witness`` ran): {"runtime_sites", "matched_sites",
    "leaked_sites"}, the static↔runtime corroboration summary.

    Additive v2 field (r14): ``wire_witness`` (only when ``ldt check
    --wire-witness`` ran): {"observed_fields", "matched_fields",
    "frames"} — how much of the runtime (msg, field) wire traffic maps
    onto the static payload schema.

    Additive v2 fields (r17): ``model_build_ms`` gains ``"mesh"`` (the
    device-semantics model), and ``compile_witness`` (only when ``ldt
    check --compile-witness`` ran): {"runtime_sites", "matched_sites",
    "recompiled_sites", "h2d_events", "d2h_events"} — how much of the
    runtime compile/transfer evidence maps onto the static jit sites.
    """
    records = []
    for f in findings:
        text = line_text_of(f) if line_text_of is not None else ""
        records.append(
            {
                "rule": f.rule,
                "rule_family": family_of(f.rule) if family_of else "general",
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "message": f.message,
                "fingerprint": fingerprint(f, text),
                "witness_pruned": bool(f.witness_pruned),
            }
        )
    payload = {
        "version": 2,
        "clean": not any(not f.witness_pruned for f in findings),
        "files_checked": files_checked,
        "grandfathered": grandfathered,
        "wall_time_ms": (timing or {}).get("wall_ms", 0.0),
        "parse_ms": (timing or {}).get("parse_ms", 0.0),
        "model_build_ms": (timing or {}).get("model_build_ms", {}),
        "findings": records,
    }
    if (timing or {}).get("leak_witness") is not None:
        payload["leak_witness"] = timing["leak_witness"]
    if (timing or {}).get("wire_witness") is not None:
        payload["wire_witness"] = timing["wire_witness"]
    if (timing or {}).get("compile_witness") is not None:
        payload["compile_witness"] = timing["compile_witness"]
    json.dump(payload, out, indent=2)
    out.write("\n")
