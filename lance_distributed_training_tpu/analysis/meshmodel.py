"""Whole-program device-semantics model (the LDT1701-1704 engine).

The compute plane's XLA-facing assumptions — mesh-axis names, partition
specs, buffer donation, jit static arguments, host-sync points — are
exactly the contracts a compiler does NOT check: a typo'd axis in a
``PartitionSpec`` compiles fine and silently replicates instead of
sharding, a donated buffer read after the call returns whatever the
compiler scribbled into it, a batch-shape-derived Python value reaching a
``static_argnames`` position recompiles the kernel per batch, and a stray
``float()`` on a device value serialises the async dispatch stream the
trainer exists to keep full. This module derives, from the one
:class:`~.concmodel.ProgramInfo` an ``ldt check`` run builds:

* every **jit site** (``jax.jit`` / ``pjit`` / ``pmap`` / ``shard_map`` —
  decorator, ``partial(jax.jit, ...)`` decorator, or wrapping call) with
  its resolved target function, ``static_argnames`` / ``static_argnums``,
  ``donate_argnums`` (the may-donate branch of a conditional counts), and
  the candidate def-site lines the runtime compile witness joins on;
* every **axis reference**: literal axis names inside
  ``PartitionSpec``/``P(...)`` calls (``with_sharding_constraint`` and
  ``shard_map`` specs included — the spec call is scanned wherever it
  appears) and literal ``axis_name`` arguments of collectives
  (``psum``/``pmean``/``pcast``/``axis_size``/...);
* **donation dataflow** (LDT1702): jit-wrapped callables tracked through
  local bindings, factory returns (``make_train_step`` returns the jit
  object), and one call level into parameters, then a branch-aware
  read-after-donate scan at every call that donates a named argument;
* **recompile dataflow** (LDT1703): ``.shape``/``len()``-derived values
  reaching static positions of jitted callables (a derivation routed
  through a declared quantized funnel — ``static-funnels`` — is
  sanctioned), plus Python ``if``/``while`` branches on parameter shapes
  inside jitted content-path functions, where shapes vary per batch;
* **host syncs** (LDT1704): ``.item()`` / ``float()``/``int()``/``bool()``
  / ``np.asarray`` coercions of device-derived values in the declared
  ``device-hot-paths`` modules, outside jitted bodies (those are LDT102's
  domain) and outside the declared ``sync-funnels``.

Everything is stdlib ``ast`` over the already-parsed module list — one
parse, one model per run, timed as ``model_build_ms["mesh"]`` in the
``--json`` report. Like the ownership model, inference is conservative:
an unresolvable callee contributes nothing, a non-literal axis name is
skipped (no false positives from guesses). The runtime half
(``utils/compiletrack.py`` + ``ldt check --compile-witness``) closes the
loop on LDT1703 with per-callsite compile counts: a hazard whose jit site
demonstrably recompiled after warmup is *reproduced*; one whose site was
exercised with a single steady-state compile is witness-pruned.
"""

from __future__ import annotations

import ast
import dataclasses
from fnmatch import fnmatch
from typing import Dict, List, Optional, Tuple

from .concmodel import ProgramInfo

__all__ = [
    "MeshModel",
    "JitSite",
    "AxisRef",
    "DonateHazard",
    "RecompileHazard",
    "SyncHazard",
    "build_mesh_model",
]

# Resolved qualnames that wrap a function for device compilation. shard_map
# and pcast/axis_size route through parallel/_compat in this repo, so dotted
# tails are matched for those.
_JIT_QNAMES = {"jax.jit", "jit", "jax.pmap", "pmap", "pjit",
               "jax.experimental.pjit.pjit"}
_JIT_TAILS = (".pjit", ".shard_map")

# Collective -> positional index of its axis_name argument.
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "ppermute": 1, "pcast": 1,
    "axis_size": 0, "axis_index": 0,
}

_SYNC_COERCIONS = ("float", "int", "bool")
_SYNC_QNAMES = {"numpy.asarray", "numpy.array", "jax.device_get"}

# jax host-metadata APIs: their results live on the host (device handles,
# process topology, abstract shapes) — calls to these never taint a value
# as device-resident.
_HOST_METADATA_QNAMES = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.process_count", "jax.process_index",
    "jax.default_backend", "jax.eval_shape", "jax.tree_util.tree_structure",
}


@dataclasses.dataclass
class JitSite:
    """One jit/pjit/pmap/shard_map wrap site."""

    kind: str          # "jit" | "pjit" | "pmap" | "shard_map"
    name: str          # display name of the wrapped callable
    module: str        # relpath of the wrap site
    line: int
    col: int
    func_key: Optional[str]        # ProgramInfo function key, when resolved
    def_module: Optional[str]      # relpath of the wrapped def
    def_lines: Tuple[int, ...]     # witness join candidates (def +
    #                                decorators + wrap line)
    node: Optional[ast.AST]        # the wrapped FunctionDef/Lambda
    params: Tuple[str, ...]
    static_argnames: Tuple[str, ...]
    static_argnums: Tuple[int, ...]
    donate_argnums: Tuple[int, ...]
    donate_conditional: bool       # donate came from one branch of an IfExp

    def witness_sites(self) -> Tuple[str, ...]:
        """``path:line`` candidates the runtime compile witness may report
        this site under — ``co_firstlineno`` points at the def or the first
        decorator depending on the interpreter, so every candidate counts."""
        if not self.def_module:
            return ()
        return tuple(f"{self.def_module}:{ln}" for ln in self.def_lines)


@dataclasses.dataclass(frozen=True)
class AxisRef:
    """One literal mesh-axis name reference."""

    axis: str
    module: str
    line: int
    col: int
    context: str  # "PartitionSpec" or "collective <name>"


@dataclasses.dataclass(frozen=True)
class DonateHazard:
    """A value passed in a donated position is read again after the call."""

    module: str
    line: int      # the donating call
    col: int
    var: str
    read_line: int
    func: str      # enclosing function key
    callee: str    # display name of the donating callable


@dataclasses.dataclass(frozen=True)
class RecompileHazard:
    """A batch-content-derived Python value steers compilation."""

    module: str
    line: int
    col: int
    detail: str
    func: str
    site: JitSite  # the jit site whose cache the value keys


@dataclasses.dataclass(frozen=True)
class SyncHazard:
    """A host-sync coercion of a device-derived value on a hot path."""

    module: str
    line: int
    col: int
    expr: str
    func: str


def _str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """Literal ``"a"`` / ``("a", "b")`` / ``["a"]`` → tuple of names; None
    for anything non-literal (conservative: unresolved statics are skipped,
    never guessed)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return tuple(out)
    return None


def _int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            got = _int_tuple(e)
            if got is None or len(got) != 1:
                return None
            out.append(got[0])
        return tuple(out)
    return None


def _params_of(fn: ast.AST) -> Tuple[str, ...]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return tuple(names)


def _pos_params(fn_node: ast.AST) -> List[str]:
    args = fn_node.args
    return [a.arg for a in args.posonlyargs + args.args]


def _base_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name through Attribute/Subscript chains: ``x.val[0]`` → x."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _match_paths(relpath: str, globs) -> bool:
    return any(fnmatch(relpath, g) for g in globs)


def _match_func_globs(fn_key: str, bare: str, globs) -> bool:
    """Function-name globs match the bare name or the dotted key tail."""
    for g in globs:
        if fnmatch(bare, g) or fnmatch(fn_key, g) \
                or fnmatch(fn_key, f"*{g}"):
            return True
    return False


class MeshModel:
    """Build with :func:`build_mesh_model` (memoized per ProgramInfo)."""

    def __init__(self, program: ProgramInfo, config):
        self.program = program
        self.mesh_axes = tuple(
            getattr(config, "mesh_axes", None)
            or ("data", "model", "seq", "pipe")
        )
        self.static_funnels = tuple(
            getattr(config, "static_funnels", None) or ()
        )
        self.sync_funnels = tuple(getattr(config, "sync_funnels", None) or ())
        self.device_hot_paths = tuple(
            getattr(config, "device_hot_paths", None) or ()
        )
        self.content_paths = tuple(getattr(config, "content_paths", None)
                                   or ())
        self.jit_sites: List[JitSite] = []
        self.axis_refs: List[AxisRef] = []
        self.donate_hazards: List[DonateHazard] = []
        self.recompile_hazards: List[RecompileHazard] = []
        self.host_syncs: List[SyncHazard] = []
        # (function key, local name) -> JitSite, plus module-level bindings
        # keyed (relpath, name). Built by the jit scan, extended by the
        # factory-return and parameter propagation passes.
        self._bound: Dict[Tuple[str, str], JitSite] = {}
        self._module_bound: Dict[Tuple[str, str], JitSite] = {}
        self._factories: Dict[str, JitSite] = {}
        self._fn_by_node = {
            id(fn.node): fn for fn in program.functions.values()
        }
        self._collect_jit_sites()
        self._collect_axis_refs()
        self._propagate_bindings()
        self._scan_donation()
        self._scan_recompile()
        self._scan_host_sync()

    # -- jit sites -----------------------------------------------------------

    def _jit_kind(self, mod, node: ast.AST) -> Optional[str]:
        """``node`` (a decorator or call func) names a jit wrapper? Returns
        the kind, unwrapping ``partial(jax.jit, ...)``."""
        qn = mod.qualname(node)
        if qn in _JIT_QNAMES or (qn or "").endswith(_JIT_TAILS) \
                or qn == "shard_map":
            tail = (qn or "").rsplit(".", 1)[-1]
            return {"jit": "jit", "pjit": "pjit", "pmap": "pmap",
                    "shard_map": "shard_map"}.get(tail, "jit")
        if isinstance(node, ast.Call):
            # Only the partial form unwraps: `jax.jit(f, ...)(x)` must NOT
            # register x — the inner call registers f on its own walk.
            fq = mod.qualname(node.func)
            if fq in ("functools.partial", "partial") and node.args:
                return self._jit_kind(mod, node.args[0])
            if not node.args:
                # `@jax.jit(static_argnames=...)` — a configured-decorator
                # call (keyword-only, so plain `jax.jit(f, ...)` wrap calls
                # never re-register through their own func).
                return self._jit_kind(mod, node.func)
        return None

    @staticmethod
    def _jit_kwargs(node: ast.AST) -> dict:
        """static/donate kwargs off the decorator or wrapping call (the
        ``partial`` call carries them in the decorator form)."""
        out = {"static_argnames": (), "static_argnums": (),
               "donate_argnums": (), "donate_conditional": False}
        if not isinstance(node, ast.Call):
            return out
        for kw in node.keywords:
            if kw.arg == "static_argnames":
                out["static_argnames"] = _str_tuple(kw.value) or ()
            elif kw.arg == "static_argnums":
                out["static_argnums"] = _int_tuple(kw.value) or ()
            elif kw.arg == "donate_argnums":
                value = kw.value
                if isinstance(value, ast.IfExp):
                    # `(0,) if donate else ()` — take the may-donate branch.
                    for branch in (value.body, value.orelse):
                        got = _int_tuple(branch)
                        if got:
                            out["donate_argnums"] = got
                            out["donate_conditional"] = True
                            break
                else:
                    out["donate_argnums"] = _int_tuple(value) or ()
        return out

    def _register_site(self, mod, kind: str, wrap_node: ast.AST,
                       target: Optional[ast.AST], name: str,
                       kwargs: dict) -> JitSite:
        fn = self._fn_by_node.get(id(target)) if target is not None else None
        def_lines: Tuple[int, ...] = ()
        def_module = None
        params: Tuple[str, ...] = ()
        if target is not None:
            def_module = mod.relpath
            lines = {target.lineno, wrap_node.lineno}
            for dec in getattr(target, "decorator_list", []):
                lines.add(dec.lineno)
            def_lines = tuple(sorted(lines))
            params = _params_of(target)
        site = JitSite(
            kind=kind, name=name, module=mod.relpath,
            line=wrap_node.lineno, col=wrap_node.col_offset,
            func_key=fn.key if fn else None,
            def_module=def_module, def_lines=def_lines,
            node=target, params=params,
            static_argnames=kwargs["static_argnames"],
            static_argnums=kwargs["static_argnums"],
            donate_argnums=kwargs["donate_argnums"],
            donate_conditional=kwargs["donate_conditional"],
        )
        self.jit_sites.append(site)
        return site

    @staticmethod
    def _nearest_def(mod, call: ast.Call, cands: List[ast.AST]):
        """Python scoping for the jitted callable's name when the module
        holds several same-named defs (two nested ``step`` functions):
        prefer a def in the call's own enclosing function, then the
        closest preceding def, then the last one."""
        if not cands:
            return None
        fn_kinds = (ast.FunctionDef, ast.AsyncFunctionDef)
        encl = mod.enclosing(call, fn_kinds)
        if encl is not None:
            local = [c for c in cands
                     if mod.enclosing(c, fn_kinds) is encl]
            if local:
                return local[-1]
        preceding = [c for c in cands if c.lineno < call.lineno]
        return (preceding or cands)[-1]

    def _collect_jit_sites(self) -> None:
        for mod in self.program.modules:
            defs_by_name: Dict[str, List[ast.AST]] = {}
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs_by_name.setdefault(node.name, []).append(node)
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        kind = self._jit_kind(mod, dec)
                        if kind:
                            self._register_site(
                                mod, kind, dec, node, node.name,
                                self._jit_kwargs(dec),
                            )
                elif isinstance(node, ast.Call):
                    kind = self._jit_kind(mod, node.func)
                    if not kind or not node.args:
                        continue
                    first = node.args[0]
                    if isinstance(first, ast.Lambda):
                        target, name = first, "<lambda>"
                    elif isinstance(first, ast.Name):
                        cands = defs_by_name.get(first.id, [])
                        target, name = self._nearest_def(mod, node, cands), \
                            first.id
                    else:
                        continue
                    site = self._register_site(
                        mod, kind, node, target, name,
                        self._jit_kwargs(node),
                    )
                    self._bind_result(mod, node, site)

    def _bind_result(self, mod, call: ast.Call, site: JitSite) -> None:
        """Track what the jit object is bound to: a local/module name
        (``step = jax.jit(f, ...)``) or a factory's return value."""
        parent = mod.parents.get(call)
        encl = mod.enclosing(call, (ast.FunctionDef, ast.AsyncFunctionDef))
        fn = self._fn_by_node.get(id(encl)) if encl is not None else None
        if isinstance(parent, ast.Assign) and parent.value is call \
                and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            name = parent.targets[0].id
            if fn is not None:
                self._bound[(fn.key, name)] = site
            else:
                self._module_bound[(mod.relpath, name)] = site
        elif isinstance(parent, ast.Return) and fn is not None:
            self._factories[fn.key] = site

    # -- axis references -----------------------------------------------------

    def _collect_axis_refs(self) -> None:
        for mod in self.program.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                qn = mod.qualname(node.func) or ""
                tail = qn.rsplit(".", 1)[-1]
                if tail == "PartitionSpec":
                    for arg in node.args:
                        elts = arg.elts if isinstance(
                            arg, (ast.Tuple, ast.List)) else [arg]
                        for e in elts:
                            if isinstance(e, ast.Constant) \
                                    and isinstance(e.value, str):
                                self.axis_refs.append(AxisRef(
                                    e.value, mod.relpath, e.lineno,
                                    e.col_offset, "PartitionSpec",
                                ))
                elif tail in _COLLECTIVES and (
                    qn.startswith("jax.") or "_compat" in qn or qn == tail
                ):
                    cands: List[ast.AST] = []
                    pos = _COLLECTIVES[tail]
                    if len(node.args) > pos:
                        cands.append(node.args[pos])
                    for kw in node.keywords:
                        if kw.arg == "axis_name":
                            cands.append(kw.value)
                    for cand in cands:
                        for axis in _str_tuple(cand) or ():
                            self.axis_refs.append(AxisRef(
                                axis, mod.relpath, cand.lineno,
                                cand.col_offset, f"collective {tail}",
                            ))

    # -- binding propagation -------------------------------------------------

    def _propagate_bindings(self) -> None:
        """Factory returns into assignment targets, then bound callables one
        call level into parameters — enough to follow
        ``train_step = make_train_step(...)`` into ``_train_loop``."""
        # A function that returns a NAME bound to a jit object is a factory
        # too (``jitted = jax.jit(step, ...); return jitted`` — the shape the
        # compile-sanitizer wrap guard produces).
        for fn in self.program.functions.values():
            if fn.key in self._factories:
                continue
            for node in self._walk_own(fn.node):
                if isinstance(node, ast.Return) \
                        and isinstance(node.value, ast.Name):
                    site = self._bound.get((fn.key, node.value.id))
                    if site is not None:
                        self._factories[fn.key] = site
                        break
        for fn in self.program.functions.values():
            mod = self.program.by_relpath.get(fn.module)
            if mod is None:
                continue
            for callee_key, call_node, _held in fn.calls:
                site = self._factories.get(callee_key)
                if site is None:
                    continue
                stmt = mod.statement_of(call_node)
                if isinstance(stmt, ast.Assign) and stmt.value is call_node \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    self._bound[(fn.key, stmt.targets[0].id)] = site
        # One level into parameters.
        param_bound: Dict[Tuple[str, str], JitSite] = {}
        for fn in self.program.functions.values():
            for callee_key, call_node, _held in fn.calls:
                callee = self.program.functions.get(callee_key)
                if callee is None:
                    continue
                pos = _pos_params(callee.node)
                for i, a in enumerate(call_node.args):
                    site = self._site_for_name(fn, a)
                    if site is not None and i < len(pos):
                        param_bound[(callee_key, pos[i])] = site
                for kw in call_node.keywords:
                    site = self._site_for_name(fn, kw.value)
                    if site is not None and kw.arg:
                        param_bound[(callee_key, kw.arg)] = site
        self._bound.update(param_bound)

    def _site_for_name(self, fn, node: ast.AST) -> Optional[JitSite]:
        if not isinstance(node, ast.Name):
            return None
        return self._bound.get((fn.key, node.id)) \
            or self._module_bound.get((fn.module, node.id))

    def _jit_calls_in(self, fn):
        """Yield ``(call_node, site)`` for every call in ``fn`` that invokes
        a known jit-wrapped callable: a bound local/param/module name, or a
        resolved edge to a decorated jitted function."""
        by_key = {
            s.func_key: s for s in self.jit_sites if s.func_key is not None
        }
        mod = self.program.by_relpath.get(fn.module)
        if mod is None:
            return
        seen = set()
        for callee_key, call_node, _held in fn.calls:
            site = by_key.get(callee_key)
            if site is not None:
                seen.add(id(call_node))
                yield call_node, site
        for node in self._walk_own(fn.node):
            if isinstance(node, ast.Call) and id(node) not in seen:
                site = self._site_for_name(fn, node.func)
                if site is not None:
                    yield node, site

    @staticmethod
    def _walk_own(node):
        """Walk a function body without descending into nested defs (they
        are their own FunctionInfo — same discipline as the concurrency
        model's body walk)."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            cur = stack.pop()
            yield cur
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(cur))

    # -- LDT1702: use-after-donate -------------------------------------------

    def _scan_donation(self) -> None:
        for fn in self.program.functions.values():
            mod = self.program.by_relpath.get(fn.module)
            if mod is None:
                continue
            for call, site in self._jit_calls_in(fn):
                if not site.donate_argnums:
                    continue
                for i in site.donate_argnums:
                    if i < len(call.args) \
                            and isinstance(call.args[i], ast.Name):
                        name = call.args[i].id
                        read = self._read_after(mod, fn, call, name)
                        if read is not None:
                            self.donate_hazards.append(DonateHazard(
                                module=fn.module, line=call.lineno,
                                col=call.col_offset, var=name,
                                read_line=read, func=fn.key,
                                callee=site.name,
                            ))

    @staticmethod
    def _binds(stmt: ast.AST, name: str) -> bool:
        """Does this statement rebind ``name`` at its top level?"""
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [stmt.target]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            targets = [stmt.target]
        flat: List[ast.AST] = []
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                flat.extend(t.elts)
            else:
                flat.append(t)
        for t in flat:
            if isinstance(t, ast.Starred):
                t = t.value
            if isinstance(t, ast.Name) and t.id == name:
                return True
        return False

    @staticmethod
    def _first_read(stmt: ast.AST, name: str) -> Optional[int]:
        """Line of the first read of ``name`` anywhere in ``stmt`` (any
        branch counts — a read on SOME path after a donate is the bug)."""
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and node.id == name \
                    and isinstance(node.ctx, ast.Load):
                return node.lineno
            if isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                return node.lineno
        return None

    def _read_after(self, mod, fn, call: ast.Call,
                    name: str) -> Optional[int]:
        """First read of ``name`` on any path after the donating ``call``
        (the same statement-ordered CFG walk discipline as the LDT1201 leak
        scan): siblings after the call's statement, then each enclosing
        block's later siblings; climbing through a loop whose body never
        rebinds the name flags the call's own next-iteration read."""
        stmt = mod.statement_of(call)
        if self._binds(stmt, name):
            return None  # the result rebinds the donated name — refreshed
        cur: ast.AST = stmt
        while cur is not fn.node:
            parent = mod.parents.get(cur)
            if parent is None:
                return None
            for field in ("body", "orelse", "finalbody"):
                block = getattr(parent, field, None)
                if isinstance(block, list) and cur in block:
                    for later in block[block.index(cur) + 1:]:
                        read = self._first_read(later, name)
                        if read is not None:
                            return read
                        if self._binds(later, name):
                            return None
                    break
            if isinstance(parent, (ast.For, ast.AsyncFor, ast.While)):
                rebound = any(
                    self._binds(s, name) for s in ast.walk(parent)
                    if isinstance(s, ast.stmt)
                )
                if not rebound:
                    # Next iteration re-reads the donated name at the call.
                    return call.lineno
                return None  # rebound somewhere in the loop: assume fresh
            cur = parent
        return None

    # -- LDT1703: recompile hazards ------------------------------------------

    def _funneled(self, mod, expr: ast.AST) -> bool:
        """Does the derivation route through a declared quantized funnel
        (``static-funnels`` name tails — coeff_chunk, pack_rows_quantum,
        ...)? A funnel clamps the value to a short ladder, so the jit cache
        sees O(1) keys instead of one per batch."""
        if not self.static_funnels:
            return False
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                qn = mod.qualname(node.func) or ""
                tail = qn.rsplit(".", 1)[-1] if qn else (
                    node.func.attr if isinstance(node.func, ast.Attribute)
                    else ""
                )
                if any(fnmatch(tail, f) for f in self.static_funnels):
                    return True
        return False

    @staticmethod
    def _shape_or_len(expr: ast.AST, params=None) -> bool:
        """Does the expression read ``.shape`` or ``len()`` (of a parameter,
        when ``params`` is given)?"""
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) and node.attr == "shape":
                if params is None or _base_name(node.value) in params:
                    return True
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                    and node.func.id == "len" and node.args:
                if params is None or _base_name(node.args[0]) in params:
                    return True
        return False

    def _shape_derived_locals(self, mod, fn) -> Dict[str, int]:
        """name → assign line for locals derived from ``.shape``/``len()``
        without a funnel in the derivation."""
        out: Dict[str, int] = {}
        for node in self._walk_own(fn.node):
            if not (isinstance(node, ast.Assign) and node.value is not None):
                continue
            if self._funneled(mod, node.value) \
                    or not self._shape_or_len(node.value):
                continue
            for t in node.targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for e in elts:
                    if isinstance(e, ast.Name):
                        out[e.id] = node.lineno
        return out

    def _in_content_paths(self, site: JitSite) -> bool:
        if site.def_module is None:
            return False
        bare = site.name
        key = site.func_key or bare
        for entry in self.content_paths:
            path_pat, _, fn_pat = entry.partition("::")
            if not fnmatch(site.def_module, path_pat):
                continue
            if not fn_pat or fnmatch(bare, fn_pat) or fnmatch(key, fn_pat) \
                    or fnmatch(key, f"*{fn_pat}"):
                return True
        return False

    def _scan_recompile(self) -> None:
        # Call-site form: shape/len-derived values into static positions.
        for fn in self.program.functions.values():
            mod = self.program.by_relpath.get(fn.module)
            if mod is None:
                continue
            derived = self._shape_derived_locals(mod, fn)

            def hazardous(expr: ast.AST) -> bool:
                if isinstance(expr, ast.Name):
                    if expr.id in derived:
                        return True
                if self._funneled(mod, expr):
                    return False
                return self._shape_or_len(expr)

            for call, site in self._jit_calls_in(fn):
                if not (site.static_argnames or site.static_argnums):
                    continue
                static_args: List[Tuple[str, ast.AST]] = []
                for kw in call.keywords:
                    if kw.arg and kw.arg in site.static_argnames:
                        static_args.append((kw.arg, kw.value))
                for i in site.static_argnums:
                    if i < len(call.args):
                        static_args.append((f"#{i}", call.args[i]))
                for label, expr in static_args:
                    if hazardous(expr):
                        self.recompile_hazards.append(RecompileHazard(
                            module=fn.module, line=call.lineno,
                            col=call.col_offset,
                            detail=(
                                f"batch-shape-derived value reaches static "
                                f"argument {label!r} of jitted "
                                f"{site.name!r}"
                            ),
                            func=fn.key, site=site,
                        ))
        # In-jit form: Python branches on parameter shapes inside jitted
        # content-path functions (shapes there vary per batch).
        for site in self.jit_sites:
            if site.node is None or not self._in_content_paths(site):
                continue
            mod = self.program.by_relpath.get(site.def_module)
            if mod is None:
                continue
            fn_key = site.func_key or site.name
            for node in self._walk_own(site.node):
                if isinstance(node, (ast.If, ast.While)) \
                        and self._shape_or_len(node.test, set(site.params)) \
                        and not self._funneled(mod, node.test):
                    self.recompile_hazards.append(RecompileHazard(
                        module=site.def_module, line=node.lineno,
                        col=node.col_offset,
                        detail=(
                            f"Python branch on a parameter shape inside "
                            f"jitted content-path function {site.name!r}"
                        ),
                        func=fn_key, site=site,
                    ))

    # -- LDT1704: hot-path host syncs ----------------------------------------

    def _device_names(self, mod, fn) -> set:
        """Fixpoint over assignments: names holding device values — results
        of jit-wrapped callables (bound names, resolved jitted defs, or a
        bare callable parameter invoked in a device-hot-path function:
        trainer-style step callbacks), jax.* calls, or values derived from
        either."""
        jit_keys = {s.func_key for s in self.jit_sites if s.func_key}
        edge_by_call = {id(c): k for k, c, _h in fn.calls}
        params = set(_params_of(fn.node))

        def device_call(node: ast.Call) -> bool:
            if edge_by_call.get(id(node)) in jit_keys:
                return True
            if self._site_for_name(fn, node.func) is not None:
                return True
            if isinstance(node.func, ast.Name) and node.func.id in params:
                return True  # step-callback parameter invoked directly
            qn = mod.qualname(node.func) or ""
            if qn in _HOST_METADATA_QNAMES:
                return False
            return qn.startswith(("jax.", "jax_"))

        assigns: List[Tuple[List[str], ast.AST]] = []
        for node in self._walk_own(fn.node):
            if isinstance(node, ast.Assign):
                names = []
                for t in node.targets:
                    elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                        else [t]
                    names.extend(
                        e.id for e in elts if isinstance(e, ast.Name)
                    )
                assigns.append((names, node.value))
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name):
                assigns.append(([node.target.id], node.value))

        device: set = set()
        changed = True
        while changed:
            changed = False
            for names, value in assigns:
                if not names or set(names) <= device:
                    continue
                tainted = False
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Call) and device_call(sub):
                        tainted = True
                        break
                    if isinstance(sub, ast.Name) and sub.id in device \
                            and isinstance(sub.ctx, ast.Load):
                        tainted = True
                        break
                if tainted:
                    before = len(device)
                    device.update(names)
                    changed = changed or len(device) > before
        return device

    def _scan_host_sync(self) -> None:
        if not self.device_hot_paths:
            return
        jitted_nodes = {
            id(s.node) for s in self.jit_sites if s.node is not None
        }
        for fn in self.program.functions.values():
            if not _match_paths(fn.module, self.device_hot_paths):
                continue
            if id(fn.node) in jitted_nodes:
                continue  # inside-jit syncs are LDT102's domain
            bare = fn.key.rsplit(".", 1)[-1]
            if self.sync_funnels \
                    and _match_func_globs(fn.key, bare, self.sync_funnels):
                continue
            mod = self.program.by_relpath.get(fn.module)
            if mod is None:
                continue
            device = self._device_names(mod, fn)
            if not device:
                continue
            for node in self._walk_own(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                qn = mod.qualname(node.func) or ""
                expr = None
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" and not node.args \
                        and _base_name(node.func.value) in device:
                    expr = f"{_base_name(node.func.value)}.item()"
                elif qn in _SYNC_COERCIONS and len(node.args) == 1 \
                        and _base_name(node.args[0]) in device:
                    expr = f"{qn}({_base_name(node.args[0])})"
                elif qn in _SYNC_QNAMES and node.args \
                        and _base_name(node.args[0]) in device:
                    expr = f"{qn}({_base_name(node.args[0])})"
                if expr is not None:
                    self.host_syncs.append(SyncHazard(
                        module=fn.module, line=node.lineno,
                        col=node.col_offset, expr=expr, func=fn.key,
                    ))

    # -- runtime witness -----------------------------------------------------

    def witness_receipt(self, witness: dict) -> dict:
        """The corroboration summary the CI compile-witness stage asserts
        on: how much of the runtime compile evidence maps onto static jit
        sites, and the transfer-event totals."""
        compiles = witness.get("compiles", {})
        static_sites = set()
        for site in self.jit_sites:
            static_sites.update(site.witness_sites())
        matched = [s for s in compiles if s in static_sites]
        transfers = witness.get("transfers", {})

        def _total(direction: str) -> int:
            return sum(
                int(entry.get("count", 0))
                for entry in transfers.get(direction, {}).values()
            )

        return {
            "runtime_sites": len(compiles),
            "matched_sites": len(matched),
            "recompiled_sites": sum(
                1 for s in matched
                if int(compiles[s].get("post_warmup", 0)) > 0
            ),
            "h2d_events": _total("h2d"),
            "d2h_events": _total("d2h"),
        }

    def witness_verdict(self, site: JitSite, witness: dict) -> str:
        """"reproduced" | "pruned" | "unknown" for an LDT1703 hazard whose
        jit site the compile witness may have exercised. Strict-evidence
        discipline: an untouched site proves nothing."""
        compiles = witness.get("compiles", {})
        entries = [
            compiles[s] for s in site.witness_sites() if s in compiles
        ]
        if not entries:
            return "unknown"
        if any(int(e.get("post_warmup", 0)) > 0 for e in entries):
            return "reproduced"
        if any(int(e.get("calls", 0)) > 1 for e in entries):
            # More than the warmup call, zero new signatures after it: the
            # predicted steady-state recompile demonstrably did not happen.
            return "pruned"
        return "unknown"


def build_mesh_model(program: ProgramInfo, config) -> MeshModel:
    """Build (or reuse) the device-semantics model for this run's
    ProgramInfo — memoized on the program instance so the LDT17xx rules,
    the ``--compile-witness`` receipt, and ``ldt graph --mesh`` share ONE
    pass (the same single-build contract as the ownership model)."""
    cached = getattr(program, "_mesh_model", None)
    if cached is not None:
        return cached
    model = MeshModel(program, config)
    program._mesh_model = model
    return model
