"""Whole-program wire-protocol evolution model (the LDT14xx engine).

Every HELLO field added since v1 (``stripe_index``/``stripe_count``,
``device_decode``, ``dataset_fingerprint``) had to be individually
remembered in ``decode_config_skew``, version-gated, and
downgrade-tolerated — and until this model, nothing but reviewer
discipline caught the PR that forgot. Like the concurrency
(:mod:`.concmodel`) and ownership (:mod:`.ownermodel`) models, this one
derives the whole contract from the already-parsed
:class:`~.concmodel.ProgramInfo` — one parse, one function table, one
model build per ``ldt check`` run — and makes it machine-checked:

* the **message vocabulary**: every ``MSG_*`` constant the protocol
  module defines (value + definition line) plus the version-gate
  constants (``PROTOCOL_VERSION``/``MIN_PROTOCOL_VERSION``/
  ``*_MIN_VERSION``);
* the **payload schema**: for each message, the fields *written* — dict
  literals handed to ``send_msg``-shaped calls, ``return MSG_X, {...}``
  handler tuples, constructor functions that return a dict literal
  (``protocol.hello``), send-forwarders (``agent._call``), and
  ``payload["k"] = v`` augmentation — and the fields *read* —
  ``req.get("k")`` / ``req["k"]`` / ``"k" in req`` on a payload variable
  whose message identity is proven by the dominating ``msg_type ==
  MSG_X`` / ``msg_type != MSG_X: raise`` guards, resolved
  **interprocedurally**: through parameters (``decode_config_skew(req)``),
  thread-spawn ``args=`` tuples (``Thread(target=self._produce,
  args=(plan, steps, req))``), handler dicts (``{MSG_X: self._handle_x}``),
  recv-forwarders (``agent._call`` returning ``recv_msg(...)``'s tuple),
  and payload-returning functions (``resolve_fleet`` → ``fleet_main``);
* the **version gates**: per function, which gate constants it compares
  against — the evidence LDT1402 demands before a version-gated field may
  be read or served outside the protocol module.

Reads *inside* the protocol module never satisfy the contract: the schema
owner validating its own fields proves nothing about the peer consuming
them — which is exactly what makes "delete one skew check in
``decode_config_skew``" an LDT1401 failure at the orphaned field.

Conservative like its siblings: an unresolvable send payload contributes
no writes but also no findings against its fields' readers only when the
witness says so — the runtime half (``utils/wiretrack.py`` +
``ldt check --wire-witness``) records which (msg, field, version) tuples
actually crossed the loopback wire and corroborates or prunes LDT1403
exactly like the lock and leak witnesses do their families.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .concmodel import FunctionInfo, ProgramInfo

__all__ = [
    "ProtoModel",
    "MessageInfo",
    "FieldSite",
    "build_proto_model",
]

# Gate-constant shape: PROTOCOL_VERSION, MIN_PROTOCOL_VERSION,
# STRIPE_MIN_VERSION, LINEAGE_MIN_VERSION, FEATURE_MIN_VERSION, ...
_GATE_RE = re.compile(r"^[A-Z][A-Z0-9_]*VERSION$")

# Resolved-callee / qualname tails that mean "this call sends a control
# frame" (arg layout: sock, msg_type, payload) or "this call receives one"
# (returns (msg_type, payload)).
_SEND_TAILS = ("send_msg",)
_RECV_TAILS = ("recv_msg",)

_TERMINAL = (ast.Raise, ast.Return, ast.Continue, ast.Break)


@dataclasses.dataclass(frozen=True)
class FieldSite:
    """One write or read of a payload field."""

    msg: str  # MSG_* name
    field: str
    module: str  # relpath
    line: int
    col: int
    func: str  # FunctionInfo key ("<module>" for module level)


@dataclasses.dataclass
class MessageInfo:
    """One MSG_* constant and its schema as the program uses it."""

    name: str
    value: Optional[int]
    line: int  # definition line in the protocol module
    writes: Dict[str, List[FieldSite]] = dataclasses.field(
        default_factory=dict
    )
    reads: Dict[str, List[FieldSite]] = dataclasses.field(
        default_factory=dict
    )
    # reads inside the protocol module — tracked separately: the schema
    # owner's own tolerant decode never satisfies the peer-read contract.
    self_reads: Dict[str, List[FieldSite]] = dataclasses.field(
        default_factory=dict
    )


def _const_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _block_terminates(body: Sequence[ast.stmt]) -> bool:
    """Does this branch always leave the current statement sequence?"""
    return bool(body) and isinstance(body[-1], _TERMINAL)


def _bind_args(target: FunctionInfo, func_expr,
               args: Sequence[ast.AST],
               keywords) -> Dict[str, ast.AST]:
    """Bind call-site argument expressions onto ``target``'s parameter
    names — the ONE implementation of the positional/keyword/self-offset
    mapping shared by send-forwarder resolution, parameter-role
    propagation, and thread-spawn ``args=`` tuples. ``func_expr`` is the
    expression the call went through: a bound-method shape (an Attribute
    on an instance — ``obj.m(...)``, ``target=self._produce``) skips the
    implicit ``self``."""
    names = [a.arg for a in target.node.args.args]
    offset = 1 if (
        target.owner is not None and isinstance(func_expr, ast.Attribute)
    ) else 0
    bound: Dict[str, ast.AST] = {}
    for i, arg in enumerate(args):
        idx = i + offset
        if idx < len(names):
            bound[names[idx]] = arg
    for kw in keywords:
        if kw.arg:
            bound[kw.arg] = kw.value
    return bound


class ProtoModel:
    """The wire-protocol schema model over a shared :class:`ProgramInfo`."""

    def __init__(self, program: ProgramInfo, config):
        self.program = program
        self.proto_path: str = getattr(
            config, "protocol_module",
            "lance_distributed_training_tpu/service/protocol.py",
        )
        binary = getattr(config, "protocol_binary", None)
        self.binary_messages: Set[str] = set(
            binary if binary is not None else ["MSG_BATCH"]
        )
        # field -> gate constant name (LDT1402 vocabulary).
        self.gated_fields: Dict[str, str] = dict(
            getattr(config, "protocol_versions", None) or {}
        )
        self.messages: Dict[str, MessageInfo] = {}
        self.msg_values: Dict[int, str] = {}
        self.gate_constants: Dict[str, int] = {}
        # fn key -> gate constant names compared anywhere in the function.
        self.fn_guards: Dict[str, Set[str]] = {}
        # fn key -> schema of the dict literal it returns
        #   {field: (module, line, col)}; the interprocedural constructor
        # map (protocol.hello, coordinator._members_payload_locked, ...).
        self.returns_schema: Dict[str, Dict[str, tuple]] = {}
        # fn key -> (msg_param_name, payload_param_name) for functions that
        # forward their parameters into a send (agent._call's send half).
        self.send_forwarders: Dict[str, Tuple[str, str]] = {}
        # fn keys whose return value is a (msg_type, payload) recv tuple
        # (agent._call's receive half).
        self.recv_forwarders: Set[str] = set()
        # fn key -> msg names its return value may be a payload of.
        self.returns_roles: Dict[str, Set[str]] = {}
        # (fn key, param name) -> msg roles, grown by the fixpoint.
        self.param_roles: Dict[Tuple[str, str], Set[str]] = {}
        # LDT1402 serve/read sites of gated fields lacking a guard:
        # (field, gate_const, module, line, col, fn key).
        self.ungated_sites: List[tuple] = []
        # send sites per msg: [(module, line)] — the topology ldt graph
        # --protocol renders (schema-resolved or not).
        self.send_sites: Dict[str, List[tuple]] = {}
        # Per-Call-node callee memo: the walk fixpoint re-visits every
        # call each round; resolution is pure in the AST, so cache by
        # node identity (ASTs outlive the model via the module cache).
        self._callee_cache: Dict[int, Optional[str]] = {}

        self._collect_protocol_constants()
        if self.messages:
            self._prepass()
            self._scan_handler_dicts()
            self._walk_fixpoint()
            self._finalize_gates()

    # -- protocol module ----------------------------------------------------

    def _collect_protocol_constants(self) -> None:
        proto = self.program.by_relpath.get(self.proto_path)
        if proto is None or proto.tree is None:
            return
        self._proto_dotted = proto.dotted_name
        for node in proto.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign):
                target = node.target
            else:
                continue
            if not isinstance(target, ast.Name):
                continue
            value = _const_int(getattr(node, "value", None))
            if target.id.startswith("MSG_"):
                self.messages[target.id] = MessageInfo(
                    name=target.id, value=value, line=node.lineno
                )
                if value is not None:
                    self.msg_values[value] = target.id
            elif _GATE_RE.match(target.id) and value is not None:
                self.gate_constants[target.id] = value

    def _msg_const(self, mod, node: ast.AST) -> Optional[str]:
        """MSG_* name a Name/Attribute resolves to, or None."""
        qn = mod.qualname(node)
        if qn is None:
            return None
        leaf = qn.rsplit(".", 1)[-1]
        if leaf in self.messages:
            # Accept both `P.MSG_X` (resolved into the protocol module) and
            # a same-module bare `MSG_X` (the protocol module itself, or a
            # star-ish re-export) — the constant name is globally unique.
            return leaf
        return None

    def _gate_const(self, mod, node: ast.AST) -> Optional[str]:
        qn = mod.qualname(node)
        if qn is None:
            return None
        leaf = qn.rsplit(".", 1)[-1]
        if leaf in self.gate_constants:
            return leaf
        return None

    def _scan_handler_dicts(self) -> None:
        """``{MSG_X: self._handle_x, ...}`` handler tables: each mapped
        method's first non-self parameter receives the corresponding
        message role (the coordinator's ``_handle_conn`` dispatch shape).
        Seeds ``param_roles`` before the walk fixpoint runs."""
        for fn in self.program.functions.values():
            mod = self.program.by_relpath[fn.module]
            cls = self.program.classes.get(fn.owner) if fn.owner else None
            for node in self.program._walk_own(fn.node):
                if not isinstance(node, ast.Dict):
                    continue
                for key, value in zip(node.keys, node.values):
                    if key is None:
                        continue
                    msg = self._msg_const(mod, key)
                    if not msg:
                        continue
                    callee = self.program._resolve_callee(
                        fn, mod, cls, {}, value
                    )
                    target = self.program.functions.get(callee) \
                        if callee else None
                    if target is None:
                        continue
                    names = [a.arg for a in target.node.args.args]
                    idx = 1 if target.owner is not None else 0
                    if idx < len(names):
                        self.param_roles.setdefault(
                            (callee, names[idx]), set()
                        ).add(msg)

    # -- pre-pass: role-independent facts ------------------------------------

    def _prepass(self) -> None:
        """Compute returns_schema (dict-literal constructors, with a small
        fixpoint through call/variable hops), send/recv forwarders, and
        per-function gate-guard sets."""
        for fn in self.program.functions.values():
            mod = self.program.by_relpath[fn.module]
            guards: Set[str] = set()
            for node in self.program._walk_own(fn.node):
                if isinstance(node, ast.Compare):
                    for sub in [node.left] + list(node.comparators):
                        gate = self._gate_const(mod, sub)
                        if gate:
                            guards.add(gate)
            if guards:
                self.fn_guards[fn.key] = guards
            self._detect_forwarders(fn, mod)
        # returns_schema fixpoint: direct dict-literal returns first, then
        # returns through locals and resolved calls (hello -> _hello,
        # _members_payload_locked -> _handle_resolve's local).
        changed = True
        iters = 0
        while changed and iters < 8:
            changed = False
            iters += 1
            for fn in self.program.functions.values():
                schema = self._returns_schema_of(fn)
                if schema and self.returns_schema.get(fn.key) != schema:
                    self.returns_schema[fn.key] = schema
                    changed = True

    def _detect_forwarders(self, fn: FunctionInfo, mod) -> None:
        params = {
            a.arg for a in list(fn.node.args.args)
            + list(fn.node.args.kwonlyargs)
        }
        for node in self.program._walk_own(fn.node):
            if not isinstance(node, ast.Call):
                continue
            if self._is_send_call(fn, mod, node) and len(node.args) >= 3:
                m, p = node.args[1], node.args[2]
                if (
                    isinstance(m, ast.Name) and m.id in params
                    and isinstance(p, ast.Name) and p.id in params
                ):
                    self.send_forwarders[fn.key] = (m.id, p.id)
        for node in self.program._walk_own(fn.node):
            if isinstance(node, ast.Return) and isinstance(
                node.value, ast.Call
            ):
                if self._is_recv_call(fn, mod, node.value):
                    self.recv_forwarders.add(fn.key)

    def _callee_tail(self, fn: FunctionInfo, mod, call: ast.Call) -> str:
        """Best-effort dotted tail of what a call targets: the resolved
        callee key when the program knows it, else the raw qualname."""
        callee = self._resolve_callee(fn, mod, call.func)
        if callee:
            return callee
        qn = mod.qualname(call.func)
        if qn:
            return qn
        if isinstance(call.func, ast.Attribute):
            return call.func.attr
        return ""

    def _is_send_call(self, fn, mod, call: ast.Call) -> bool:
        tail = self._callee_tail(fn, mod, call)
        return any(
            tail == t or tail.endswith("." + t) for t in _SEND_TAILS
        )

    def _is_recv_call(self, fn, mod, call: ast.Call) -> bool:
        tail = self._callee_tail(fn, mod, call)
        if any(tail == t or tail.endswith("." + t) for t in _RECV_TAILS):
            return True
        return tail in self.recv_forwarders

    def _resolve_callee(self, fn: FunctionInfo, mod, func_expr
                        ) -> Optional[str]:
        """ProgramInfo's resolver plus `local = self.attr` typing (the
        `svc = self.service` idiom the server's handshake path uses)."""
        key = id(func_expr)
        if key in self._callee_cache:
            return self._callee_cache[key]
        cls = self.program.classes.get(fn.owner) if fn.owner else None
        local_types = self._local_types(fn, mod, cls)
        got = self.program._resolve_callee(
            fn, mod, cls, local_types, func_expr
        )
        self._callee_cache[key] = got
        return got

    def _local_types(self, fn: FunctionInfo, mod, cls) -> Dict[str, str]:
        cached = getattr(fn, "_proto_local_types", None)
        if cached is not None:
            return cached
        local: Dict[str, str] = {}
        for node in self.program._walk_own(fn.node):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                continue
            name, value = node.targets[0].id, node.value
            if isinstance(value, ast.Call):
                ckey = self.program._resolve_class(mod, value.func)
                if ckey:
                    local[name] = ckey
            elif (
                isinstance(value, ast.Attribute)
                and isinstance(value.value, ast.Name)
                and value.value.id == "self"
                and cls is not None
            ):
                # `svc = self.service` — type from the annotated attr.
                keys = self.program._attr_class_keys(cls, value.attr)
                if len(keys) == 1:
                    local[name] = keys[0]
        fn._proto_local_types = local
        return local

    def _returns_schema_of(self, fn: FunctionInfo
                           ) -> Optional[Dict[str, tuple]]:
        """Schema of the dict this function returns, when that is a single
        dict literal (directly, via a local, or via a schema-returning
        call). Functions with multiple differently-shaped returns get the
        union — good enough for constructors, which have one."""
        mod = self.program.by_relpath[fn.module]
        local_schemas = self._literal_schemas(fn, mod)
        out: Dict[str, tuple] = {}
        for node in self.program._walk_own(fn.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            schema = self._expr_schema(fn, mod, node.value, local_schemas)
            if schema:
                out.update(schema)
        return out or None

    def _literal_schemas(self, fn: FunctionInfo, mod
                         ) -> Dict[str, Dict[str, tuple]]:
        """name -> dict-literal schema for locals assigned a dict literal
        (or a schema-returning call), augmented by `name["k"] = v`. Two
        ordered passes: literal/call assigns first, then augmentations —
        `_walk_own` has no statement order, and the agent's
        ``payload["pressure"] = ...`` sits inside an if/try after the
        literal."""
        out: Dict[str, Dict[str, tuple]] = {}
        for node in self.program._walk_own(fn.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                schema = self._expr_schema(fn, mod, node.value, out)
                if schema is not None:
                    out[node.targets[0].id] = dict(schema)
        for node in self.program._walk_own(fn.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)
                and isinstance(node.targets[0].value, ast.Name)
            ):
                target = node.targets[0]
                key = _const_str(target.slice)
                name = target.value.id
                if key is not None and name in out:
                    out[name][key] = (
                        fn.module, target.lineno, target.col_offset
                    )
        return out

    def _expr_schema(self, fn, mod, expr, local_schemas
                     ) -> Optional[Dict[str, tuple]]:
        if isinstance(expr, ast.Dict):
            schema: Dict[str, tuple] = {}
            for k in expr.keys:
                key = _const_str(k) if k is not None else None
                if key is not None:
                    schema[key] = (fn.module, k.lineno, k.col_offset)
            return schema
        if isinstance(expr, ast.Name) and expr.id in local_schemas:
            return dict(local_schemas[expr.id])
        if isinstance(expr, ast.Call):
            callee = self._resolve_callee(fn, mod, expr.func)
            if callee and callee in self.returns_schema:
                return dict(self.returns_schema[callee])
        return None

    # -- main walk (role fixpoint) -------------------------------------------

    def _walk_fixpoint(self) -> None:
        """Walk every function collecting writes/reads; parameter, spawn,
        and return role propagation converges in a few passes (roles only
        grow)."""
        for _round in range(6):
            before = (
                {k: set(v) for k, v in self.param_roles.items()},
                {k: set(v) for k, v in self.returns_roles.items()},
            )
            for m in self.messages.values():
                m.writes.clear()
                m.reads.clear()
                m.self_reads.clear()
            self.ungated_sites = []
            self.send_sites = {}
            for fn in self.program.functions.values():
                _FnWalk(self, fn).run()
            after = (
                {k: set(v) for k, v in self.param_roles.items()},
                {k: set(v) for k, v in self.returns_roles.items()},
            )
            if after == before:
                break

    # -- recording (called by _FnWalk) ---------------------------------------

    def record_send(self, fn: FunctionInfo, mod, msg: str, payload_expr,
                    local_schemas, line: int) -> None:
        if msg in self.binary_messages:
            return
        info = self.messages.get(msg)
        if info is None:
            return
        self.send_sites.setdefault(msg, []).append((fn.module, line))
        schema = None
        if payload_expr is not None:
            schema = self._expr_schema(fn, mod, payload_expr, local_schemas)
        if not schema:
            return
        for field, (module, fline, fcol) in schema.items():
            info.writes.setdefault(field, []).append(FieldSite(
                msg=msg, field=field, module=module, line=fline, col=fcol,
                func=fn.key,
            ))

    def record_read(self, fn: FunctionInfo, msgs: Set[str], field: str,
                    line: int, col: int) -> None:
        for msg in msgs:
            if msg in self.binary_messages:
                continue
            info = self.messages.get(msg)
            if info is None:
                continue
            site = FieldSite(
                msg=msg, field=field, module=fn.module, line=line, col=col,
                func=fn.key,
            )
            if fn.module == self.proto_path:
                info.self_reads.setdefault(field, []).append(site)
            else:
                info.reads.setdefault(field, []).append(site)
                # Qualified ("MSG_HELLO.stripe_index") entries scope the
                # gate to one message; a bare field name gates it
                # everywhere (RESOLVE_OK's membership stripe_count is NOT
                # the version-gated HELLO field of the same name).
                gate = self.gated_fields.get(f"{msg}.{field}") \
                    or self.gated_fields.get(field)
                if gate:
                    self._note_gated(fn, field, gate, line, col)

    def record_gated_kwarg(self, fn: FunctionInfo, field: str, line: int,
                           col: int) -> None:
        """A gated field passed by keyword into a schema constructor — a
        serve site that needs the same guard a read does. Matched by field
        name across qualified entries (a constructor serves whatever
        message its schema is sent as)."""
        if fn.module == self.proto_path:
            return
        gate = self.gated_fields.get(field)
        if gate is None:
            for key, value in self.gated_fields.items():
                if key.endswith("." + field):
                    gate = value
                    break
        if gate:
            self._note_gated(fn, field, gate, line, col)

    def _note_gated(self, fn: FunctionInfo, field: str, gate: str,
                    line: int, col: int) -> None:
        if not self._guard_verdicts(gate).get(fn.key, False):
            self.ungated_sites.append(
                (field, gate, fn.module, line, col, fn.key)
            )

    def _callers_map(self) -> Dict[str, List[str]]:
        cached = getattr(self, "_callers_cache", None)
        if cached is None:
            cached = {}
            for caller in self.program.functions.values():
                for callee, _n, _h in caller.calls:
                    cached.setdefault(callee, []).append(caller.key)
            self._callers_cache = cached
        return cached

    def _guard_verdicts(self, gate: str) -> Dict[str, bool]:
        """fn key -> "every call chain into this function passes a
        comparison against ``gate``". The semantics per function: it holds
        the guard itself, or it has callers and EVERY caller is guarded
        (the `_hello` helper whose one caller `_dial_member` holds the
        guard). Computed whole-graph per gate — Tarjan SCCs of the caller
        graph in dependency order, then a greatest-fixpoint inside each
        SCC — so diamond caller graphs and recursive helper chains get
        their correct verdict instead of a path-order-dependent one
        (naive memoized DFS poisons shared intermediates with
        cycle-contaminated False). A caller cycle with no external entry
        and no internal guard is unguarded, like any other uncalled
        function."""
        cached = getattr(self, "_gate_verdict_cache", None)
        if cached is None:
            cached = self._gate_verdict_cache = {}
        if gate in cached:
            return cached[gate]
        callers = self._callers_map()
        has_guard = {
            fn for fn, gates in self.fn_guards.items() if gate in gates
        }
        verdict: Dict[str, bool] = {}

        def settle(members: List[str]) -> None:
            """Verdict for one SCC; every caller OUTSIDE it is already
            settled (Tarjan pops successor components first)."""
            inside = set(members)
            if len(members) == 1 and members[0] not in callers.get(
                members[0], ()
            ):
                fn = members[0]
                cs = callers.get(fn, ())
                verdict[fn] = fn in has_guard or (
                    bool(cs) and all(verdict.get(c, False) for c in cs)
                )
                return
            external = any(
                c not in inside
                for m in members
                for c in callers.get(m, ())
            )
            if not external and not (inside & has_guard):
                for m in members:
                    verdict[m] = False
                return
            # Greatest fixpoint: start optimistic, refute until stable —
            # a recursion back-edge is not an unguarded entry; only real
            # external paths (and missing internal guards) refute.
            for m in members:
                verdict[m] = True
            changed = True
            while changed:
                changed = False
                for m in members:
                    cs = callers.get(m, ())
                    value = m in has_guard or (
                        bool(cs)
                        and all(verdict.get(c, False) for c in cs)
                    )
                    if value != verdict[m]:
                        verdict[m] = value
                        changed = True

        # Iterative Tarjan over the caller graph (successors = callers):
        # components pop callers-first, exactly the settle() order.
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        counter = [0]
        for root in self.program.functions:
            if root in index:
                continue
            work = [(root, iter(callers.get(root, ())))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, succs = work[-1]
                advanced = False
                for succ in succs:
                    if succ not in self.program.functions:
                        continue
                    if succ not in index:
                        index[succ] = low[succ] = counter[0]
                        counter[0] += 1
                        stack.append(succ)
                        on_stack.add(succ)
                        work.append(
                            (succ, iter(callers.get(succ, ())))
                        )
                        advanced = True
                        break
                    if succ in on_stack:
                        low[node] = min(low[node], index[succ])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    members = []
                    while True:
                        top = stack.pop()
                        on_stack.discard(top)
                        members.append(top)
                        if top == node:
                            break
                    settle(members)
        cached[gate] = verdict
        return verdict

    def _finalize_gates(self) -> None:
        self.ungated_sites.sort(key=lambda s: (s[2], s[3], s[4]))

    # -- rule queries --------------------------------------------------------

    def orphan_writes(self) -> List[FieldSite]:
        """LDT1401: fields some sender writes that no peer module reads.
        One finding per (msg, field), at the first write site."""
        out = []
        for name in sorted(self.messages):
            info = self.messages[name]
            for field in sorted(info.writes):
                if field in info.reads:
                    continue
                sites = sorted(
                    info.writes[field], key=lambda s: (s.module, s.line)
                )
                out.append(sites[0])
        return out

    def orphan_reads(self) -> List[FieldSite]:
        """LDT1403: fields some peer reads that no sender writes — dead
        drift (a removed field still consumed, or a typo'd key). One
        finding per read site."""
        out = []
        for name in sorted(self.messages):
            info = self.messages[name]
            for field in sorted(info.reads):
                if field in info.writes:
                    continue
                out.extend(sorted(
                    info.reads[field], key=lambda s: (s.module, s.line)
                ))
        return out

    def _field_in_traffic(self, key: str) -> bool:
        """Does a protocol-versions entry's field appear anywhere in the
        modeled schema (written, read, or validated)?"""
        if "." in key:
            msg, field = key.split(".", 1)
            info = self.messages.get(msg)
            infos = [info] if info is not None else []
        else:
            field = key
            infos = list(self.messages.values())
        return any(
            field in i.writes or field in i.reads or field in i.self_reads
            for i in infos
        )

    def config_drift(self) -> List[str]:
        """Gate constants named in [tool.ldt-check.protocol-versions] that
        the protocol module does not define — reported only for entries
        whose field actually appears in the modeled traffic. An entry
        naming a message or field outside the scanned protocol is scoped
        config (inert here), like a dispatch-table row for an unscanned
        module; one guarding LIVE traffic with a nonexistent constant is
        a broken gate nobody can ever satisfy."""
        missing = set()
        for key, gate in self.gated_fields.items():
            if gate in self.gate_constants:
                continue
            if not self._field_in_traffic(key):
                continue
            missing.add(gate)
        return sorted(missing)

    def witness_receipt(self, witness: dict) -> dict:
        """Corroboration summary for the --json report / CI receipt: how
        much of the runtime (msg, field) evidence maps onto the static
        schema."""
        fields = witness.get("fields", {})
        observed = 0
        matched = 0
        for value, field_counts in fields.items():
            name = self.msg_values.get(int(value))
            info = self.messages.get(name) if name else None
            for field in field_counts:
                observed += 1
                if info is not None and (
                    field in info.writes
                    or field in info.reads
                    or field in info.self_reads
                ):
                    matched += 1
        return {
            "observed_fields": observed,
            "matched_fields": matched,
            "frames": sum(
                int(n) for n in witness.get("frames", {}).values()
            ),
            # Negotiated versions the run actually exercised — the
            # receipt's proof that the interop matrix covered more than
            # one protocol generation.
            "versions_seen": sorted({
                int(v)
                for versions in witness.get("versions", {}).values()
                for v in versions
            }),
        }

    def witness_verdict(self, witness: dict, site: FieldSite) -> str:
        """"pruned" | "reproduced" | "unknown" for an LDT1403 orphan-read
        against the wire witness. Pruned when the (msg, field) tuple was
        observed crossing the wire (a writer exists outside the static
        view); reproduced when the message was exercised and the field
        never appeared."""
        info = self.messages.get(site.msg)
        if info is None or info.value is None:
            return "unknown"
        value = str(info.value)
        fields = witness.get("fields", {}).get(value, {})
        if int(fields.get(site.field, 0)) > 0:
            return "pruned"
        if int(witness.get("frames", {}).get(value, 0)) > 0:
            return "reproduced"
        return "unknown"


class _FnWalk:
    """One function's statement-ordered walk: payload-role tracking under
    msg-type guards, schema sends, field reads."""

    def __init__(self, model: ProtoModel, fn: FunctionInfo):
        self.model = model
        self.fn = fn
        self.mod = model.program.by_relpath[fn.module]
        self.cls = (
            model.program.classes.get(fn.owner) if fn.owner else None
        )
        # Safe to cache across walk rounds: literal schemas depend only on
        # returns_schema, which the prepass fixpoint froze before the
        # first round.
        cached = getattr(fn, "_proto_schemas", None)
        if cached is None:
            cached = model._literal_schemas(fn, self.mod)
            fn._proto_schemas = cached
        self.local_schemas = cached
        # payload var -> roles (None = known payload, message unproven).
        self.roles: Dict[str, Optional[Set[str]]] = {}
        # msg-type var -> payload var it was received with.
        self.partner: Dict[str, str] = {}
        # Parameters with roles from the interprocedural fixpoint.
        for arg in list(fn.node.args.args) + list(fn.node.args.kwonlyargs):
            got = model.param_roles.get((fn.key, arg.arg))
            if got:
                self.roles[arg.arg] = set(got)

    def run(self) -> None:
        self._block(self.fn.node.body)

    # -- statements ----------------------------------------------------------

    def _block(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        model, fn, mod = self.model, self.fn, self.mod
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs walk as their own FunctionInfo
        if isinstance(stmt, ast.If):
            self._if(stmt)
            return
        if isinstance(stmt, (ast.While, ast.For)):
            if isinstance(stmt, ast.For):
                self._exprs([stmt.iter])
            else:
                self._exprs([stmt.test])
            self._block(stmt.body)
            self._block(stmt.orelse)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._exprs([item.context_expr])
            self._block(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self._block(stmt.body)
            for handler in stmt.handlers:
                self._block(handler.body)
            self._block(stmt.orelse)
            self._block(stmt.finalbody)
            return
        if isinstance(stmt, ast.Return):
            self._return(stmt)
            self._exprs([stmt.value] if stmt.value is not None else [])
            return
        if isinstance(stmt, ast.Assign):
            self._assign(stmt)
            return
        self._exprs([stmt])

    def _assign(self, stmt: ast.Assign) -> None:
        model, fn, mod = self.model, self.fn, self.mod
        value = stmt.value
        if len(stmt.targets) == 1 and isinstance(stmt.targets[0], ast.Tuple):
            elts = stmt.targets[0].elts
            # `msg_type, payload = recv_msg(...)` (or a recv-forwarder).
            if (
                len(elts) == 2
                and all(isinstance(e, ast.Name) for e in elts)
                and isinstance(value, ast.Call)
                and model._is_recv_call(fn, mod, value)
            ):
                self.partner[elts[0].id] = elts[1].id
                self.roles[elts[1].id] = None
                self._exprs([value])
                return
            # `reply_type, reply = MSG_X, {...}` — a handler's deferred
            # send pairing (the coordinator's error arms).
            if (
                len(elts) == 2
                and isinstance(value, ast.Tuple)
                and len(value.elts) == 2
            ):
                msg = model._msg_const(mod, value.elts[0])
                if msg:
                    model.record_send(
                        fn, mod, msg, value.elts[1], self.local_schemas,
                        stmt.lineno,
                    )
        if (
            len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
        ):
            name = stmt.targets[0].id
            # `payload = resolve_fleet(...)` — a payload-returning callee.
            if isinstance(value, ast.Call):
                callee = model._resolve_callee(fn, mod, value.func)
                got = model.returns_roles.get(callee) if callee else None
                if got:
                    self.roles[name] = set(got)
        self._exprs([value])

    def _return(self, stmt: ast.Return) -> None:
        model, fn, mod = self.model, self.fn, self.mod
        value = stmt.value
        if value is None:
            return
        # `return MSG_X, payload` — the coordinator handler contract.
        if isinstance(value, ast.Tuple) and len(value.elts) == 2:
            msg = model._msg_const(mod, value.elts[0])
            if msg:
                model.record_send(
                    fn, mod, msg, value.elts[1], self.local_schemas,
                    stmt.lineno,
                )
                return
        # `return reply` where reply carries proven roles.
        if isinstance(value, ast.Name):
            roles = self.roles.get(value.id)
            if roles:
                model.returns_roles.setdefault(fn.key, set()).update(roles)

    # -- guards --------------------------------------------------------------

    def _guard_of(self, test: ast.AST):
        """(msgvar, msg, is_eq, rest_exprs) for a msg-type comparison test,
        else None. BoolOp(And, [guard, rest...]) applies the guard to the
        rest of its own test too (`msg_type == MSG_ERROR and MARKER in
        reply.get("message")`)."""
        model, mod = self.model, self.mod
        rest: List[ast.AST] = []
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And) \
                and test.values:
            rest = list(test.values[1:])
            test = test.values[0]
        if not (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.Eq, ast.NotEq))
        ):
            return None
        left, right = test.left, test.comparators[0]
        for var_node, const_node in ((left, right), (right, left)):
            if isinstance(var_node, ast.Name) and var_node.id in self.partner:
                msg = model._msg_const(mod, const_node)
                if msg:
                    return (
                        var_node.id, msg,
                        isinstance(test.ops[0], ast.Eq), rest,
                    )
        return None

    def _if(self, stmt: ast.If) -> None:
        guard = self._guard_of(stmt.test)
        if guard is None:
            self._exprs([stmt.test])
            self._block(stmt.body)
            self._block(stmt.orelse)
            return
        msgvar, msg, is_eq, rest = guard
        payload = self.partner[msgvar]
        outer = self.roles.get(payload)
        if is_eq:
            # Reads in the rest of the same And-test see the narrowed role.
            self.roles[payload] = {msg}
            self._exprs(rest)
            self._block(stmt.body)
            self.roles[payload] = outer
            self._block(stmt.orelse)
        else:
            self._exprs(rest)
            self._block(stmt.body)
            if _block_terminates(stmt.body):
                # `if msg_type != MSG_X: raise` — everything after is X.
                self._block(stmt.orelse)
                self.roles[payload] = {msg}
            else:
                self.roles[payload] = {msg}
                self._block(stmt.orelse)
                self.roles[payload] = outer

    # -- expressions ---------------------------------------------------------

    def _exprs(self, nodes) -> None:
        for top in nodes:
            if top is None:
                continue
            stack = [top]
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(node, ast.Call):
                    self._call(node)
                elif isinstance(node, ast.Subscript):
                    self._subscript(node)
                elif isinstance(node, ast.Compare):
                    self._compare_in(node)
                stack.extend(ast.iter_child_nodes(node))

    def _roles_of(self, node: ast.AST) -> Optional[Set[str]]:
        if isinstance(node, ast.Name):
            return self.roles.get(node.id)
        return None

    def _call(self, call: ast.Call) -> None:
        model, fn, mod = self.model, self.fn, self.mod
        # payload.get("field" [, default])
        func = call.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "get"
            and isinstance(func.value, ast.Name)
        ):
            roles = self.roles.get(func.value.id)
            if roles and call.args:
                field = _const_str(call.args[0])
                if field is not None:
                    model.record_read(
                        fn, roles, field, call.lineno, call.col_offset
                    )
        # send_msg(sock, MSG_X, payload) — direct or through a forwarder.
        if model._is_send_call(fn, mod, call) and len(call.args) >= 3:
            msg = model._msg_const(mod, call.args[1])
            if msg:
                model.record_send(
                    fn, mod, msg, call.args[2], self.local_schemas,
                    call.lineno,
                )
        callee = model._resolve_callee(fn, mod, func)
        if callee in model.send_forwarders:
            # fn(msg, payload) forwarding both into a send: map the call
            # site's constant + payload expr through the parameter names.
            msg_param, payload_param = model.send_forwarders[callee]
            target = model.program.functions.get(callee)
            if target is not None:
                bound = _bind_args(
                    target, func, call.args, call.keywords
                )
                msg = model._msg_const(mod, bound.get(msg_param)) \
                    if bound.get(msg_param) is not None else None
                if msg and bound.get(payload_param) is not None:
                    model.record_send(
                        fn, mod, msg, bound[payload_param],
                        self.local_schemas, call.lineno,
                    )
        # Parameter-role propagation into the resolved callee, positional
        # and keyword; `threading.Thread(target=..., args=(...))` spawns
        # map their args tuple onto the target's parameters.
        qn = mod.qualname(func)
        if qn == "threading.Thread":
            self._spawn_roles(call)
        elif callee:
            self._param_roles(call, callee, func)
        # Gated fields served by keyword into a schema constructor —
        # record_gated_kwarg owns the gate lookup (bare AND qualified
        # "MSG_X.field" entries); no pre-filter here, a bare-name check
        # against qualified keys would silently disable the serve half.
        if callee and callee in model.returns_schema:
            for kw in call.keywords:
                if kw.arg:
                    model.record_gated_kwarg(
                        fn, kw.arg, call.lineno, call.col_offset
                    )

    def _param_roles(self, call: ast.Call, callee: str, func) -> None:
        model = self.model
        target = model.program.functions.get(callee)
        if target is None:
            return
        for name, arg in _bind_args(
            target, func, call.args, call.keywords
        ).items():
            roles = self._roles_of(arg)
            if roles:
                model.param_roles.setdefault(
                    (callee, name), set()
                ).update(roles)

    def _spawn_roles(self, call: ast.Call) -> None:
        model, fn, mod = self.model, self.fn, self.mod
        cls = model.program.classes.get(fn.owner) if fn.owner else None
        target_key = model.program._spawn_target(
            fn, mod, cls, model._local_types(fn, mod, cls), call
        )
        target = model.program.functions.get(target_key) \
            if target_key else None
        if target is None:
            return
        spawn_target = next(
            (kw.value for kw in call.keywords if kw.arg == "target"), None
        )
        args_kw = next(
            (kw.value for kw in call.keywords if kw.arg == "args"), None
        )
        if not isinstance(args_kw, ast.Tuple):
            return
        for name, arg in _bind_args(
            target, spawn_target, args_kw.elts, ()
        ).items():
            roles = self._roles_of(arg)
            if roles:
                model.param_roles.setdefault(
                    (target_key, name), set()
                ).update(roles)

    def _subscript(self, node: ast.Subscript) -> None:
        if not isinstance(node.value, ast.Name):
            return
        roles = self.roles.get(node.value.id)
        if not roles:
            return
        field = _const_str(node.slice)
        if field is None:
            return
        if isinstance(node.ctx, ast.Store):
            return  # augmentation handled by the schema pass
        self.model.record_read(
            self.fn, roles, field, node.lineno, node.col_offset
        )

    def _compare_in(self, node: ast.Compare) -> None:
        # `"field" in payload`
        if len(node.ops) != 1 or not isinstance(node.ops[0], ast.In):
            return
        comp = node.comparators[0]
        if not isinstance(comp, ast.Name):
            return
        roles = self.roles.get(comp.id)
        if not roles:
            return
        field = _const_str(node.left)
        if field is not None:
            self.model.record_read(
                self.fn, roles, field, node.lineno, node.col_offset
            )

def build_proto_model(program: ProgramInfo, config) -> ProtoModel:
    """Build (or reuse) the wire-protocol model for this run's ProgramInfo
    — memoized on the program instance so the LDT14xx rules, the
    ``--wire-witness`` receipt, and ``ldt graph --protocol`` share ONE
    schema pass (the same single-build contract as the ownership model)."""
    cached = getattr(program, "_proto_model", None)
    if cached is not None:
        return cached
    model = ProtoModel(program, config)
    program._proto_model = model
    return model
