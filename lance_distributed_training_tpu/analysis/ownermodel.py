"""Ownership/lifecycle dataflow + content-purity taint (LDT12xx/LDT13xx).

The loader-graph refactor (ROADMAP keystone) reshuffles exactly the code
whose invariants per-module AST rules cannot see: who owns a BufferPool
page, a shm slot token, a socket, a thread — across ``try/finally``,
early returns, generator closes, and handoffs between functions — and
which values are allowed to influence the *content* of the stream versus
only its *capacity*. This module derives both models in one pass over the
already-built :class:`~.concmodel.ProgramInfo` (no second AST walk — the
satellite contract is ONE parse, ONE function table per ``ldt check`` run):

* the **ownership model**: every acquisition of a resource named in the
  ``[tool.ldt-check.resources]`` vocabulary (``BufferPool.lease`` →
  ``release``, shm slot token → ack-put, ``socket.socket`` → ``close``,
  non-daemon ``threading.Thread`` → ``join``, ``AutoTuner`` → ``stop``)
  is tracked through a per-function control-flow walk with exception
  edges (any statement that can raise while a resource is held is an exit
  path), ``finally`` joins, early ``return``\\ s, and generator-close
  edges (a ``yield`` is a potential exit: ``close()`` raises GeneratorExit
  there). Ownership *transfers* end tracking: returning the handle,
  putting it on a queue, storing it on ``self`` or into a container,
  passing it as a keyword argument (the ``out=`` convention), registering
  it with a callback, or handing it to a function the interprocedural
  fixpoint proved publishes or releases its parameter (the
  ``_publish_conn``/``_release_host`` idioms). What survives to an exit
  still *held* is a leak-on-path; a second release on a non-idempotent
  kind is a double-release; any use after a release is a use-after-release.

* the **purity model**: functions declared content paths
  (``[tool.ldt-check.content-paths]``: batch assembly, plan generation,
  cursor arithmetic, lineage digests) and everything they reach inside
  content modules must be free of nondeterminism taint sources — wall
  clocks, unseeded RNG, thread identity, set-iteration order, pops off
  queue-typed attributes (multi-producer arrival order), and autotune
  actuator setters. This pins statically the "actuation changes capacity,
  never content" separation the autotuner's bit-identical-stream benches
  only assert empirically.

The model is conservative exactly like the concurrency model: an
unresolved call contributes no ownership transfer edges and no reachable
taint — silence where the analyzer cannot see, findings only where it
can. The runtime witness (``utils/leaktrack.py`` + ``ldt check
--leak-witness``) closes the gap with evidence, mirroring the lock
witness: a static leak whose acquire site demonstrably leaked at runtime
is *reproduced*; one whose site was exercised and always balanced is
``witness_pruned`` (rendered, not failing).
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .concmodel import FunctionInfo, ProgramInfo

__all__ = [
    "OwnerModel",
    "ResourceSpec",
    "AcquireRecord",
    "LifecycleIssue",
    "TaintHit",
    "DEFAULT_TAINT_SOURCES",
    "build_owner_model",
]

# Ownership states (may-analysis: a var's state is a SET of these).
_HELD = "held"
_RELEASED = "released"
_XFER = "transferred"

# Call-attribute names that hand ownership to another holder: queues,
# containers, executors, callback registries. A tracked handle passed as a
# positional argument to one of these is transferred, not leaked.
_SINK_ATTRS = {
    "put", "put_nowait", "append", "appendleft", "add", "send", "submit",
    "extend", "insert", "register", "add_done_callback",
}
_SINK_QUALNAMES = {"weakref.finalize", "atexit.register"}

# Methods ON a tracked handle that do not constitute an exception edge:
# activation/config calls that only fail on programmer error (`t.start()`
# on a started thread, `sock.settimeout` on a closed fd). bind / listen /
# connect / send / recv stay raise points — those failing mid-setup is
# exactly the fd-leak class LDT1201 exists for.
_NONRAISY_METHODS = {
    "start", "settimeout", "setsockopt", "set", "clear", "is_alive",
    "is_set", "getsockname", "fileno", "locked",
}


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    """One vocabulary entry: how a resource kind is acquired and released.
    ``idempotent`` kinds (``BufferPool.release`` ignores foreign/returned
    pages; ``socket.close`` is re-callable) skip the double-release rule —
    use-after-release still applies."""

    kind: str
    acquire: Tuple[str, ...]
    release: Tuple[str, ...]
    describe: str = ""
    idempotent: bool = False


# The repo vocabulary (overridable via [tool.ldt-check.resources]). Acquire
# patterns match the resolved callee's dotted tail, or — normalization
# fallback for untyped attributes — the raw attribute chain with case and
# underscores folded (`self.buffer_pool.lease` matches `BufferPool.lease`).
DEFAULT_RESOURCES: Dict[str, dict] = {
    "pool-page": {
        "acquire": ["BufferPool.lease"],
        "release": ["release", "release_batch"],
        "describe": "BufferPool page lease",
        "idempotent": True,
    },
    "shm-token": {
        "acquire": ["ShmSlotWriter._acquire"],
        "release": ["put", "put_nowait", "release_token"],
        "describe": "shm ring slot token",
        # A double-put hands one slot to two writers: memory corruption.
        "idempotent": False,
    },
    "socket": {
        "acquire": ["socket.socket", "socket.create_connection"],
        "release": ["close"],
        "describe": "socket",
        "idempotent": True,
    },
    "thread": {
        # Non-daemon threads only (the factory skips daemon=True spawns:
        # LDT201 owns the daemon-or-join policy; ownership tracks joins).
        "acquire": ["threading.Thread"],
        "release": ["join"],
        "describe": "non-daemon thread",
        "idempotent": True,
    },
    "autotuner": {
        "acquire": ["AutoTuner"],
        "release": ["stop"],
        "describe": "autotune controller",
        "idempotent": True,
    },
}

# Nondeterminism taint sources (call qualnames; bare names match the call's
# attribute/function name — the actuator-setter entries). Extended, not
# replaced, by [tool.ldt-check] taint-sources.
DEFAULT_TAINT_SOURCES: Tuple[str, ...] = (
    # wall clocks & monotonic clocks — time must never shape content
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    # unseeded/global RNG (seeded np.random.default_rng(...) is fine: its
    # method calls hang off a Call, which has no resolvable qualname here)
    "random.random", "random.randint", "random.randrange", "random.choice",
    "random.shuffle", "random.sample", "random.getrandbits", "random.uniform",
    "numpy.random.permutation", "numpy.random.shuffle",
    "numpy.random.randint", "numpy.random.random", "numpy.random.rand",
    "numpy.random.choice",
    # identity — varies per run/thread/process
    "threading.get_ident", "threading.current_thread",
    "uuid.uuid4", "uuid.uuid1", "os.urandom", "os.getpid",
    "secrets.token_hex", "secrets.token_bytes",
    # autotune actuator setters: capacity knobs must never steer content
    "set_prefetch", "set_budget", "set_workers",
)


@dataclasses.dataclass
class AcquireRecord:
    """One tracked acquisition. ``leak`` is set by the flow when some path
    exits the function with the resource still held."""

    kind: str
    module: str  # relpath
    line: int
    col: int
    func: str  # FunctionInfo key
    var: str
    leak: Optional[str] = None  # "exception" | "return" | "generator-close"

    def site(self) -> str:
        return f"{self.module}:{self.line}"


@dataclasses.dataclass(frozen=True)
class LifecycleIssue:
    """A double-release or use-after-release at a specific site."""

    issue: str  # "double-release" | "use-after-release"
    kind: str
    module: str
    line: int
    col: int
    func: str
    var: str
    acquire_line: int


@dataclasses.dataclass(frozen=True)
class TaintHit:
    """A nondeterminism source reachable from a declared content path."""

    source: str
    module: str
    line: int
    col: int
    func: str  # function containing the source
    content_root: str  # the declared content function it is reachable from


def _norm(part: str) -> str:
    return part.replace("_", "").lower()


class OwnerModel:
    """The ownership + purity model over a shared :class:`ProgramInfo`."""

    def __init__(self, program: ProgramInfo, config):
        self.program = program
        self.specs = self._parse_specs(config)
        # Interprocedural roles (fixpoint over the resolved call graph):
        self.acquirers: Dict[str, str] = {}  # fn key -> kind it returns fresh
        self.releasers: Dict[str, str] = {}  # fn key -> kind of released param
        self.transferers: Set[str] = set()   # fn key publishes/stores a param
        self.records: List[AcquireRecord] = []
        self.issues: List[LifecycleIssue] = []
        self.taints: List[TaintHit] = []
        # (mod, cls, local_types) per function key — the fixpoint and the
        # flow both resolve through these; building them once per function
        # keeps the whole model build linear in program size.
        self._ctx_cache: Dict[str, tuple] = {}
        self._interproc_fixpoint()
        for fn in self.program.functions.values():
            _Flow(self, fn).run()
        self._record_inline_acquires()
        self.records.sort(key=lambda r: (r.module, r.line, r.col))
        self.issues.sort(key=lambda i: (i.module, i.line, i.col))
        self._build_purity(config)

    # -- vocabulary ---------------------------------------------------------

    @staticmethod
    def _parse_specs(config) -> List[ResourceSpec]:
        raw = getattr(config, "resources", None) or DEFAULT_RESOURCES
        specs = []
        for kind, entry in raw.items():
            specs.append(ResourceSpec(
                kind=kind,
                acquire=tuple(entry.get("acquire", ())),
                release=tuple(entry.get("release", ())),
                describe=entry.get("describe", kind),
                idempotent=bool(entry.get("idempotent", False)),
            ))
        return specs

    def spec(self, kind: str) -> ResourceSpec:
        for s in self.specs:
            if s.kind == kind:
                return s
        raise KeyError(kind)

    @staticmethod
    def _match_tail(pattern: str, candidate: Optional[str]) -> bool:
        """Dotted-tail match with case/underscore folding, so the pattern
        ``BufferPool.lease`` matches both the resolved callee key
        ``…buffers.BufferPool.lease`` and the raw untyped attribute chain
        ``self.buffer_pool.lease``."""
        if not candidate:
            return False
        pparts = pattern.split(".")
        cparts = candidate.split(".")
        if len(cparts) < len(pparts):
            return False
        return all(
            _norm(p) == _norm(c)
            for p, c in zip(pparts, cparts[-len(pparts):])
        )

    def acquire_kind(self, fn, mod, cls, local_types,
                     call: ast.Call) -> Optional[str]:
        """Resource kind a call acquires, or None. Resolution order:
        a function the fixpoint proved returns a fresh resource, then the
        configured acquire patterns against the resolved callee and the
        raw qualname."""
        callee = self.program._resolve_callee(fn, mod, cls, local_types,
                                              call.func)
        if callee in self.acquirers:
            return self.acquirers[callee]
        qn = mod.qualname(call.func)
        for spec in self.specs:
            for pat in spec.acquire:
                if self._match_tail(pat, callee) or self._match_tail(pat, qn):
                    if pat.endswith("threading.Thread") or pat == "Thread":
                        # Daemon spawns are LDT201's jurisdiction (daemon OR
                        # join); ownership tracks joinable threads only.
                        for kw in call.keywords:
                            if kw.arg == "daemon" and isinstance(
                                kw.value, ast.Constant
                            ) and kw.value.value is True:
                                return None
                    return spec.kind
        return None

    def _record_inline_acquires(self) -> None:
        """Register ``return pool.lease(...)`` wrapper sites as (immediately
        transferred) acquire records: no finding is possible there, but the
        site is a real runtime acquisition point the leak witness keys by,
        and the ownership graph should show the wrapper as an acquirer."""
        seen = {(r.module, r.line) for r in self.records}
        for fn in self.program.functions.values():
            mod, cls, local_types = self._fn_ctx(fn)
            for node in self.program._walk_own(fn.node):
                if not (isinstance(node, ast.Return)
                        and isinstance(node.value, ast.Call)):
                    continue
                kind = self.acquire_kind(fn, mod, cls, local_types,
                                         node.value)
                if kind and (fn.module, node.value.lineno) not in seen:
                    seen.add((fn.module, node.value.lineno))
                    self.records.append(AcquireRecord(
                        kind=kind, module=fn.module,
                        line=node.value.lineno,
                        col=node.value.col_offset, func=fn.key,
                        var="<returned>",
                    ))

    def acquire_sites(self) -> Set[str]:
        """Every static acquire site (``relpath:line``) — the join keys the
        runtime leak witness maps onto."""
        return {r.site() for r in self.records}

    # -- interprocedural roles ----------------------------------------------

    def _interproc_fixpoint(self) -> None:
        """Grow the acquirer/releaser/transferer sets until stable: a
        function returning a fresh resource makes its callers' call sites
        acquire sites; a function releasing a bare parameter makes calls
        passing a handle releases; a function storing a parameter on
        ``self`` (the ``_publish`` handle-swap) or into a sink makes such
        calls transfers."""
        changed = True
        iters = 0
        while changed and iters < 20:
            changed = False
            iters += 1
            for fn in self.program.functions.values():
                kind = self._returns_fresh(fn)
                if kind and self.acquirers.get(fn.key) != kind:
                    self.acquirers[fn.key] = kind
                    changed = True
                kind = self._releases_param(fn)
                if kind and self.releasers.get(fn.key) != kind:
                    self.releasers[fn.key] = kind
                    changed = True
                if fn.key not in self.transferers and \
                        self._publishes_param(fn):
                    self.transferers.add(fn.key)
                    changed = True

    def _fn_ctx(self, fn: FunctionInfo):
        """(mod, cls, local_types) for resolving calls inside ``fn`` —
        local types come from ``name = ClassName(...)`` assignments plus
        annotated parameters (``buffer_pool: Optional[BufferPool]``).
        Cached per function key (the fixpoint revisits every function)."""
        cached = self._ctx_cache.get(fn.key)
        if cached is not None:
            return cached
        program = self.program
        mod = program.by_relpath[fn.module]
        cls = program.classes.get(fn.owner) if fn.owner else None
        local_types: Dict[str, str] = {}
        args = getattr(fn.node, "args", None)
        if args is not None:
            for arg in list(args.args) + list(args.kwonlyargs):
                if arg.annotation is None:
                    continue
                name = ProgramInfo._annotation_name(arg.annotation)
                ckey = program._class_by_name(name)
                if ckey:
                    local_types[arg.arg] = ckey
        for node in program._walk_own(fn.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                ckey = program._resolve_class(mod, node.value.func)
                if ckey:
                    local_types[node.targets[0].id] = ckey
        self._ctx_cache[fn.key] = (mod, cls, local_types)
        return mod, cls, local_types

    def _param_names(self, fn: FunctionInfo) -> List[str]:
        args = getattr(fn.node, "args", None)
        if args is None:
            return []
        names = [a.arg for a in list(args.args) + list(args.kwonlyargs)]
        return [n for n in names if n != "self"]

    def _returns_fresh(self, fn: FunctionInfo) -> Optional[str]:
        """Kind this function returns a freshly-acquired resource of:
        ``return pool.lease(...)`` directly, or acquire-to-local + a
        ``return local`` somewhere."""
        mod, cls, local_types = self._fn_ctx(fn)
        acquired: Dict[str, str] = {}
        for node in self.program._walk_own(fn.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                kind = self.acquire_kind(fn, mod, cls, local_types,
                                         node.value)
                if kind:
                    acquired[node.targets[0].id] = kind
            elif isinstance(node, ast.Return) and node.value is not None:
                if isinstance(node.value, ast.Call):
                    kind = self.acquire_kind(fn, mod, cls, local_types,
                                             node.value)
                    if kind:
                        return kind
                if isinstance(node.value, ast.Name) and \
                        node.value.id in acquired:
                    return acquired[node.value.id]
        return None

    def release_names(self, kind: Optional[str] = None) -> Set[str]:
        """Normalized release-method names, for one kind or all."""
        out: Set[str] = set()
        for spec in self.specs:
            if kind is None or spec.kind == kind:
                out |= {_norm(r) for r in spec.release}
        return out

    def _release_targets(self, fn, mod, cls, local_types, call: ast.Call,
                         names: Set[str], release_names: Set[str],
                         kind: Optional[str]) -> Set[str]:
        """Subset of ``names`` this call releases: ``var.close()``,
        ``pool.release(var)``, or a resolved releaser callee taking var."""
        out: Set[str] = set()
        func = call.func
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id in names \
                    and _norm(func.attr) in release_names:
                out.add(func.value.id)
            if _norm(func.attr) in release_names:
                for a in call.args:
                    if isinstance(a, ast.Name) and a.id in names:
                        out.add(a.id)
        callee = self.program._resolve_callee(fn, mod, cls, local_types,
                                              func)
        if callee in self.releasers and (
            kind is None or self.releasers[callee] == kind
        ):
            for a in call.args:
                if isinstance(a, ast.Name) and a.id in names:
                    out.add(a.id)
        return out

    def _releases_param(self, fn: FunctionInfo) -> Optional[str]:
        params = set(self._param_names(fn))
        if not params:
            return None
        mod, cls, local_types = self._fn_ctx(fn)
        all_release = self.release_names()
        for node in self.program._walk_own(fn.node):
            if not isinstance(node, ast.Call):
                continue
            hit = self._release_targets(fn, mod, cls, local_types, node,
                                        params, all_release, None)
            if hit:
                # Kind attribution: the release-method name decides (the
                # first spec claiming it). Ambiguous names (close/put) pick
                # the first matching spec — acceptable: releasers are an
                # is-a-release fact, kinds only gate double-release.
                func = node.func
                attr = _norm(func.attr) if isinstance(func, ast.Attribute) \
                    else ""
                for spec in self.specs:
                    if attr in {_norm(r) for r in spec.release}:
                        return spec.kind
                callee = self.program._resolve_callee(
                    fn, mod, cls, local_types, func
                )
                if callee in self.releasers:
                    return self.releasers[callee]
        return None

    def _publishes_param(self, fn: FunctionInfo) -> bool:
        """True when a bare parameter is stored on ``self``/a container or
        handed to a sink — callers passing a handle have transferred it."""
        params = set(self._param_names(fn))
        if not params:
            return False
        for node in self.program._walk_own(fn.node):
            if isinstance(node, ast.Assign):
                if not any(
                    isinstance(v, ast.Name) and v.id in params
                    for v in ast.walk(node.value)
                ):
                    continue
                for t in node.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        return True
            elif isinstance(node, ast.Call):
                if _is_sink_call(node, self.program.by_relpath[fn.module]):
                    for a in node.args:
                        if isinstance(a, ast.Name) and a.id in params:
                            return True
        return False

    # -- purity --------------------------------------------------------------

    def _build_purity(self, config) -> None:
        entries = list(getattr(config, "content_paths", None) or ())
        if not entries:
            return
        parsed = []  # (path_glob, fn_glob)
        for entry in entries:
            path_glob, _, fn_glob = entry.partition("::")
            parsed.append((path_glob, fn_glob or "*"))
        module_globs = [p for p, _f in parsed]

        def in_content_modules(fn: FunctionInfo) -> bool:
            return any(
                fnmatch.fnmatch(fn.module, g) for g in module_globs
            )

        roots = [
            fn for fn in self.program.functions.values()
            if any(
                fnmatch.fnmatch(fn.module, pg)
                and fnmatch.fnmatch(fn.key, fg)
                for pg, fg in parsed
            )
        ]
        sources = tuple(DEFAULT_TAINT_SOURCES) + tuple(
            getattr(config, "taint_sources", None) or ()
        )
        # Reachability: BFS from each declared content function through
        # resolved calls, bounded to content modules — a content function
        # timing itself via the obs layer does not drag telemetry code
        # into content scope.
        reach_root: Dict[str, str] = {}
        for root in roots:
            stack = [root.key]
            while stack:
                cur = stack.pop()
                if cur in reach_root:
                    continue
                reach_root[cur] = root.key
                cur_fn = self.program.functions.get(cur)
                if cur_fn is None:
                    continue
                for callee, _n, _h in cur_fn.calls:
                    sub = self.program.functions.get(callee)
                    if sub is not None and callee not in reach_root and \
                            in_content_modules(sub):
                        stack.append(callee)
        seen: Set[tuple] = set()
        for key, root_key in reach_root.items():
            fn = self.program.functions.get(key)
            if fn is None:
                continue
            for hit in self._scan_taint(fn, sources):
                src, node = hit
                dedup = (fn.module, node.lineno, node.col_offset, src)
                if dedup in seen:
                    continue
                seen.add(dedup)
                self.taints.append(TaintHit(
                    source=src, module=fn.module, line=node.lineno,
                    col=node.col_offset, func=key, content_root=root_key,
                ))
        self.taints.sort(key=lambda t: (t.module, t.line, t.col))

    def _scan_taint(self, fn: FunctionInfo, sources):
        mod = self.program.by_relpath[fn.module]
        cls = self.program.classes.get(fn.owner) if fn.owner else None
        for node in self.program._walk_own(fn.node):
            if isinstance(node, ast.Call):
                qn = mod.qualname(node.func)
                attr = node.func.attr if isinstance(node.func, ast.Attribute) \
                    else None
                for src in sources:
                    if "." in src:
                        if qn == src:
                            yield src, node
                            break
                    elif qn == src or attr == src:
                        yield src, node
                        break
                else:
                    # Multi-producer queue pop: .get/.get_nowait on a
                    # self-attribute the class model typed as a queue —
                    # arrival order is scheduler order, never content
                    # order.
                    if attr in ("get", "get_nowait") and cls is not None \
                            and isinstance(node.func, ast.Attribute):
                        base = node.func.value
                        if (
                            isinstance(base, ast.Attribute)
                            and isinstance(base.value, ast.Name)
                            and base.value.id == "self"
                        ):
                            ctors = cls.attr_ctors.get(base.attr, ())
                            if any("queue" in c.lower() or
                                   c.endswith("Queue") for c in ctors):
                                yield "queue-pop-order", node
            elif isinstance(node, ast.For):
                # Iterating a set iterates hash order — per-process salt.
                it = node.iter
                if isinstance(it, ast.Set) or (
                    isinstance(it, ast.Call)
                    and mod.qualname(it.func) in ("set", "frozenset")
                ):
                    yield "set-iteration-order", node


def _is_sink_call(call: ast.Call, mod) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in _SINK_ATTRS:
        return True
    # Bare-name callback registration (`register(sock)`, a `finalize`
    # parameter): the callee's NAME declares the handoff even when the
    # callee itself cannot be resolved.
    if isinstance(func, ast.Name) and func.id in _SINK_ATTRS:
        return True
    qn = mod.qualname(func)
    return qn in _SINK_QUALNAMES


# -- per-function flow -------------------------------------------------------


class _BlockOut:
    """Exit channels of one statement block."""

    __slots__ = ("normal", "raised", "returned", "broke", "continued")

    def __init__(self):
        self.normal: Optional[dict] = None
        self.raised: List[dict] = []
        self.returned: List[dict] = []
        self.broke: List[dict] = []
        self.continued: List[dict] = []


def _merge(*envs) -> Optional[dict]:
    """May-join: union of states per record (absent = not acquired on that
    path, contributes nothing)."""
    live = [e for e in envs if e is not None]
    if not live:
        return None
    out: dict = {}
    for env in live:
        for rid, states in env.items():
            out[rid] = out.get(rid, frozenset()) | states
    return out


class _Flow:
    """Path-sensitive ownership walk of one function body."""

    def __init__(self, model: OwnerModel, fn: FunctionInfo):
        self.model = model
        self.fn = fn
        self.mod, self.cls, self.local_types = model._fn_ctx(fn)
        self.binding: Dict[str, AcquireRecord] = {}
        self.records: List[AcquireRecord] = []
        self.is_generator = any(
            isinstance(n, (ast.Yield, ast.YieldFrom))
            for n in model.program._walk_own(fn.node)
        )

    def run(self) -> None:
        # Fast path: functions with no acquire events need no flow.
        if not self._has_acquires():
            return
        out = self._flow_block(self.fn.node.body, {})
        exits = [
            ("return", _merge(out.normal, *out.returned)),
            ("exception", _merge(*out.raised)),
        ]
        for channel, env in exits:
            if env is None:
                continue
            for rec in self.records:
                if _HELD in env.get(id(rec), frozenset()) and rec.leak is None:
                    rec.leak = (
                        "generator-close" if channel == "exception"
                        and self.is_generator else channel
                    )
        self.model.records.extend(self.records)

    def _has_acquires(self) -> bool:
        for node in self.model.program._walk_own(self.fn.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and self.model.acquire_kind(
                    self.fn, self.mod, self.cls, self.local_types, node.value
                )
            ):
                return True
        return False

    # -- block/statement walk ------------------------------------------------

    def _flow_block(self, body: Sequence[ast.stmt], env: dict) -> _BlockOut:
        out = _BlockOut()
        cur: Optional[dict] = dict(env)
        for stmt in body:
            if cur is None:
                break  # unreachable tail after return/raise/break
            cur = self._flow_stmt(stmt, cur, out)
        out.normal = cur
        return out

    def _flow_stmt(self, stmt: ast.stmt, env: dict,
                   out: _BlockOut) -> Optional[dict]:
        if isinstance(stmt, ast.If):
            then_env, else_env = self._refine_guard(stmt.test, env)
            self._expr_events(stmt.test, then_env, out)
            t = self._flow_block(stmt.body, then_env)
            e = self._flow_block(stmt.orelse, else_env)
            self._fold(out, t, e)
            return _merge(t.normal, e.normal)
        if isinstance(stmt, (ast.While, ast.For)):
            if isinstance(stmt, ast.For):
                env = self._expr_events(stmt.iter, env, out)
            else:
                env = self._expr_events(stmt.test, env, out)
            b = self._flow_block(stmt.body, env)
            out.raised.extend(b.raised)
            out.returned.extend(b.returned)
            merged = _merge(env, b.normal, *b.broke, *b.continued)
            o = self._flow_block(stmt.orelse, merged or env)
            self._fold(out, o)
            return _merge(merged, o.normal)
        if isinstance(stmt, ast.Try):
            return self._flow_try(stmt, env, out)
        if isinstance(stmt, ast.With) or isinstance(stmt, ast.AsyncWith):
            for item in stmt.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Name) and ctx.id in self.binding:
                    # `with sock:` — the context manager owns teardown.
                    env = self._transition(env, ctx.id, _XFER)
                else:
                    env = self._expr_events(ctx, env, out)
            b = self._flow_block(stmt.body, env)
            self._fold(out, b)
            return b.normal
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                env = self._transfer_names_in(stmt.value, env)
            out.returned.append(env)
            return None
        if isinstance(stmt, ast.Raise):
            out.raised.append(env)
            return None
        if isinstance(stmt, ast.Break):
            out.broke.append(env)
            return None
        if isinstance(stmt, ast.Continue):
            out.continued.append(env)
            return None
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A closure capturing a tracked handle escapes it (the
            # placement plane's `produce` pattern).
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) and node.id in self.binding:
                    env = self._transition(env, node.id, _XFER)
            return env
        return self._apply_stmt(stmt, env, out)

    def _fold(self, out: _BlockOut, *blocks: _BlockOut) -> None:
        for b in blocks:
            out.raised.extend(b.raised)
            out.returned.extend(b.returned)
            out.broke.extend(b.broke)
            out.continued.extend(b.continued)

    def _flow_try(self, stmt: ast.Try, env: dict,
                  out: _BlockOut) -> Optional[dict]:
        body = self._flow_block(stmt.body, env)
        handler_in = _merge(*body.raised) or dict(env)
        handler_normals: List[Optional[dict]] = []
        pre_raised: List[dict] = []
        pre_returned: List[dict] = list(body.returned)
        catches_all = False
        for handler in stmt.handlers:
            if handler.type is None or self._is_broad(handler.type):
                catches_all = True
            h = self._flow_block(handler.body, handler_in)
            handler_normals.append(h.normal)
            pre_raised.extend(h.raised)
            pre_returned.extend(h.returned)
            out.broke.extend(h.broke)
            out.continued.extend(h.continued)
        if not stmt.handlers or not catches_all:
            # Typed handlers leave other exception classes escaping with
            # the body's mid-flight state — the balancer fd-leak class.
            pre_raised.extend(body.raised)
        orelse = self._flow_block(stmt.orelse, body.normal or {})
        self._fold(out, orelse)
        pre_raised.extend(orelse.raised)
        pre_returned.extend(orelse.returned)
        out.broke.extend(body.broke)
        out.continued.extend(body.continued)
        pre_normal = _merge(
            orelse.normal if stmt.orelse else body.normal, *handler_normals
        )
        if stmt.finalbody:
            # The finally runs on every channel; flow it once over the
            # join and re-split (standard conservative approximation — a
            # `finally: release(x)` marks x released on all of them).
            joined = _merge(pre_normal, *pre_raised, *pre_returned)
            f = self._flow_block(stmt.finalbody, joined or {})
            self._fold(out, f)
            if f.normal is None:
                return None  # finally itself always exits
            if pre_raised:
                out.raised.append(f.normal)
            if pre_returned:
                out.returned.append(f.normal)
            return f.normal if pre_normal is not None else None
        out.raised.extend(pre_raised)
        out.returned.extend(pre_returned)
        return pre_normal

    @staticmethod
    def _is_broad(type_node: ast.AST) -> bool:
        names = []
        if isinstance(type_node, ast.Name):
            names = [type_node.id]
        elif isinstance(type_node, ast.Tuple):
            names = [e.id for e in type_node.elts if isinstance(e, ast.Name)]
        return any(n in ("Exception", "BaseException") for n in names)

    def _refine_guard(self, test: ast.AST, env: dict) -> Tuple[dict, dict]:
        """None-guard path refinement: under ``if sock is not None:`` the
        else branch cannot hold the resource (the acquire never happened on
        that path) — without this, the standard ``except: if sock: close``
        cleanup reads as a leak."""
        then_env, else_env = dict(env), dict(env)

        def drop(e: dict, name: str) -> dict:
            rec = self.binding.get(name)
            if rec is not None and id(rec) in e:
                e = dict(e)
                e[id(rec)] = frozenset([_XFER])
            return e

        name = None
        positive = True
        if isinstance(test, ast.Name):
            name = test.id
        elif isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
                and isinstance(test.operand, ast.Name):
            name, positive = test.operand.id, False
        elif isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                isinstance(test.left, ast.Name) and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None:
            name = test.left.id
            positive = isinstance(test.ops[0], ast.IsNot)
        if name is not None and name in self.binding:
            if positive:
                else_env = drop(else_env, name)
            else:
                then_env = drop(then_env, name)
        return then_env, else_env

    # -- statement effects ---------------------------------------------------

    def _transition(self, env: dict, name: str, state: str) -> dict:
        rec = self.binding.get(name)
        if rec is None:
            return env
        env = dict(env)
        env[id(rec)] = frozenset([state])
        return env

    def _states(self, env: dict, name: str) -> frozenset:
        rec = self.binding.get(name)
        if rec is None:
            return frozenset()
        return env.get(id(rec), frozenset())

    def _transfer_names_in(self, expr: ast.AST, env: dict) -> dict:
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in self.binding:
                env = self._transition(env, node.id, _XFER)
        return env

    def _expr_events(self, expr: Optional[ast.AST], env: dict,
                     out: _BlockOut) -> dict:
        if expr is None:
            return env
        holder = ast.Expr(value=expr)
        ast.copy_location(holder, expr)
        return self._apply_stmt(holder, env, out) or env

    def _apply_stmt(self, stmt: ast.stmt, env: dict,
                    out: _BlockOut) -> Optional[dict]:
        entry_env = env
        model = self.model
        tracked = set(self.binding)
        consumed: Set[int] = set()  # id(ast node) already explained
        releases: List[Tuple[str, ast.Call]] = []
        transfers: Set[str] = set()
        uses: List[Tuple[str, ast.AST]] = []
        acquire_target: Optional[Tuple[str, str, ast.Call]] = None
        raisy = False

        value = getattr(stmt, "value", None)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(value, ast.Call):
            kind = model.acquire_kind(self.fn, self.mod, self.cls,
                                      self.local_types, value)
            if kind:
                acquire_target = (stmt.targets[0].id, kind, value)
                consumed.add(id(value.func))

        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                if acquire_target is not None and node is acquire_target[2]:
                    continue
                handled = False
                if tracked:
                    kinds = {self.binding[n].kind for n in tracked}
                    rel_names: Set[str] = set()
                    for k in kinds:
                        rel_names |= model.release_names(k)
                    hit = model._release_targets(
                        self.fn, self.mod, self.cls, self.local_types,
                        node, tracked, rel_names, None,
                    )
                    for name in hit:
                        # The name must be released under ITS kind's verbs
                        # (a socket is not released by `put`).
                        if _norm_call_matches(
                            model, node, self.binding[name].kind,
                            self, name,
                        ):
                            releases.append((name, node))
                            handled = True
                            self._consume_name(node, name, consumed)
                    if not handled and _is_sink_call(node, self.mod):
                        for a in node.args:
                            if isinstance(a, ast.Name) and a.id in tracked:
                                transfers.add(a.id)
                                consumed.add(id(a))
                                handled = True
                    callee = model.program._resolve_callee(
                        self.fn, self.mod, self.cls, self.local_types,
                        node.func,
                    )
                    if callee in model.transferers:
                        for a in node.args:
                            if isinstance(a, ast.Name) and a.id in tracked:
                                transfers.add(a.id)
                                consumed.add(id(a))
                                handled = True
                    for kw in node.keywords:
                        if isinstance(kw.value, ast.Name) and \
                                kw.value.id in tracked:
                            # Keyword passing (the numpy `out=` convention)
                            # is a deliberate handoff.
                            transfers.add(kw.value.id)
                            consumed.add(id(kw.value))
                    if not handled and isinstance(node.func, ast.Attribute) \
                            and isinstance(node.func.value, ast.Name) \
                            and node.func.value.id in tracked \
                            and node.func.attr in _NONRAISY_METHODS:
                        handled = True  # a use, but not an exception edge
                if not handled:
                    raisy = True
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load):
                raisy = True
            elif isinstance(node, (ast.Yield, ast.YieldFrom)):
                pass  # handled after the walk

        # Assignments whose RHS mentions a tracked handle alias/store it.
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)) and \
                value is not None:
            for node in ast.walk(value):
                if isinstance(node, ast.Name) and node.id in tracked and \
                        id(node) not in consumed:
                    transfers.add(node.id)
                    consumed.add(id(node))

        # Remaining loads are plain uses.
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                    and node.id in tracked and id(node) not in consumed:
                uses.append((node.id, node))

        # Apply: releases (double-release check), transfers, uses
        # (use-after-release check), in that order.
        for name, call in releases:
            rec = self.binding[name]
            states = self._states(env, name)
            if _RELEASED in states and \
                    not model.spec(rec.kind).idempotent:
                model.issues.append(LifecycleIssue(
                    issue="double-release", kind=rec.kind,
                    module=self.fn.module, line=call.lineno,
                    col=call.col_offset, func=self.fn.key, var=name,
                    acquire_line=rec.line,
                ))
            env = self._transition(env, name, _RELEASED)
        for name in transfers:
            env = self._transition(env, name, _XFER)
        reported: Set[tuple] = set()
        for name, node in uses:
            rec = self.binding[name]
            states = self._states(env, name)
            key = (name, node.lineno)
            if _RELEASED in states and key not in reported:
                reported.add(key)
                model.issues.append(LifecycleIssue(
                    issue="use-after-release", kind=rec.kind,
                    module=self.fn.module, line=node.lineno,
                    col=node.col_offset, func=self.fn.key, var=name,
                    acquire_line=rec.line,
                ))

        # Exception edge: the statement can raise with the PRE-statement
        # states (the release/transfer may not have happened yet).
        if raisy and any(
            _HELD in entry_env.get(id(rec), frozenset())
            for rec in self.binding.values()
        ):
            out.raised.append(entry_env)

        # Generator-close edge: a yield is a potential exit (close() raises
        # GeneratorExit there). The yielded value was already delivered, so
        # transfer it first, then snapshot.
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Yield, ast.YieldFrom)):
                if node.value is not None:
                    env = self._transfer_names_in(node.value, env)
                if any(
                    _HELD in env.get(id(rec), frozenset())
                    for rec in self.binding.values()
                ):
                    out.raised.append(env)

        # New acquisition / rebinds LAST (they shadow the old handle).
        if acquire_target is not None:
            name, kind, call = acquire_target
            rec = AcquireRecord(
                kind=kind, module=self.fn.module, line=call.lineno,
                col=call.col_offset, func=self.fn.key, var=name,
            )
            self.binding[name] = rec
            self.records.append(rec)
            env = dict(env)
            env[id(rec)] = frozenset([_HELD])
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id in self.binding:
            # Rebound to something untracked. Stop tracking only when the
            # handle was already released on this path (the close-then-
            # redial pattern: the name now holds a fresh foreign value).
            # A rebind while still held is the branch-alternative pattern
            # (`dst = pool.lease(...) if pool else np.empty(...)` split
            # across if/else) — the original acquisition stays live on its
            # own path and must keep flowing to its transfer/release.
            name = stmt.targets[0].id
            if not (isinstance(value, ast.Name) and value.id == name) and \
                    _RELEASED in self._states(env, name):
                self.binding.pop(name, None)
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    self.binding.pop(t.id, None)
        return env

    def _consume_name(self, call: ast.Call, name: str,
                      consumed: Set[int]) -> None:
        func = call.func
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and func.value.id == name:
            consumed.add(id(func.value))
        for a in call.args:
            if isinstance(a, ast.Name) and a.id == name:
                consumed.add(id(a))


def _norm_call_matches(model: OwnerModel, call: ast.Call, kind: str,
                       flow: _Flow, name: str) -> bool:
    """Does this call release ``name`` under ``kind``'s own verbs?"""
    hit = model._release_targets(
        flow.fn, flow.mod, flow.cls, flow.local_types, call, {name},
        model.release_names(kind), kind,
    )
    return name in hit


def build_owner_model(program: ProgramInfo, config) -> OwnerModel:
    """Build (or reuse) the ownership/purity model for this run's
    ProgramInfo — memoized on the program instance so the LDT12xx and
    LDT13xx rule families, the ``--leak-witness`` summary, and ``ldt graph
    --ownership`` all share ONE dataflow pass (the satellite contract:
    one parse, one function table, one ownership walk per run)."""
    cached = getattr(program, "_owner_model", None)
    if cached is not None:
        return cached
    model = OwnerModel(program, config)
    program._owner_model = model
    return model
