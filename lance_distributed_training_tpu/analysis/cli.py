"""``ldt check`` / ``ldt graph`` — the distributed-training lint CLI.

``check``: exit status is the gate contract — 0 when no NEW findings
(relative to the baseline, when one exists), 1 when new findings are
reported, 2 on usage errors. ``--update-baseline`` grandfathers the current
findings so the gate can be adopted incrementally and ratcheted down.
``--lock-witness`` feeds a runtime lock-order witness (emitted by the test
suite under ``LDT_LOCK_SANITIZER=1``) into the LDT1001 cross-check:
observed orderings corroborate static cycles, contradicted ones prune.
``--leak-witness`` is the same loop for the LDT1201 ownership family: a
runtime lease witness (``LDT_LEAK_SANITIZER=1``, ``utils/leaktrack.py``)
corroborates leaks that reproduced and prunes exercised-and-balanced
sites, and the report carries the match summary so CI can assert the
static and runtime halves still overlap.

``graph``: render the cross-module concurrency model (spawned-thread
roots, the locks each thread path acquires, the lock-order edges) as
Graphviz DOT (``--dot``) or a text summary — the machine-checked topology
the README renders. ``--ownership`` adds the resource-ownership model:
resource kinds as diamond nodes, acquire→release edges, red edges for
leak-on-path findings.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from .config import load_config
from .core import (
    all_rules,
    analyze_project,
    load_baseline,
    parse_modules,
    split_new_findings,
    write_baseline,
)
from .reporters import render_json, render_text

__all__ = ["check_main", "build_check_parser", "graph_main"]


def build_check_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ldt check",
        description="AST-based distributed-training lint "
                    "(rules LDT001-LDT1301; config in [tool.ldt-check])",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to check (default: configured paths)")
    p.add_argument("--root", default=".",
                   help="repo root: config + baseline live here, reported "
                        "paths are relative to it")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--update-baseline", action="store_true",
                   help="write current findings to the baseline file and "
                        "exit 0 — future runs fail only on NEW findings")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding as new")
    p.add_argument("--lock-witness", default=None, metavar="PATH",
                   help="runtime lock-order witness JSON (emitted by a "
                        "test run under LDT_LOCK_SANITIZER=1): observed "
                        "orderings corroborate LDT1001 cycles, "
                        "contradicted ones are marked witness_pruned and "
                        "do not fail the gate")
    p.add_argument("--leak-witness", default=None, metavar="PATH",
                   help="runtime resource-lease witness JSON (emitted by "
                        "a test run under LDT_LEAK_SANITIZER=1, "
                        "utils/leaktrack.py): sites that demonstrably "
                        "leaked corroborate LDT1201 findings, exercised-"
                        "and-balanced sites mark them witness_pruned")
    p.add_argument("--wire-witness", default=None, metavar="PATH",
                   help="runtime wire-traffic witness JSON (emitted by a "
                        "test run under LDT_WIRE_SANITIZER=1, "
                        "utils/wiretrack.py): a (msg, field) tuple "
                        "observed crossing the wire prunes the LDT1403 "
                        "orphan-read at that field (a writer exists "
                        "outside the static view); a message exercised "
                        "without the field corroborates it")
    p.add_argument("--compile-witness", default=None, metavar="PATH",
                   help="runtime compile/transfer witness JSON (emitted by "
                        "a test run under LDT_COMPILE_SANITIZER=1, "
                        "utils/compiletrack.py): a jit site that "
                        "demonstrably recompiled after warmup corroborates "
                        "the LDT1703 hazard there; one exercised with a "
                        "single steady-state signature marks it "
                        "witness_pruned")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    return p


def _rel_site(site: str, root: str) -> str:
    """Relativize a witness ``abspath:line`` site to ``root`` — the one
    join-key discipline BOTH witness families share (the static models
    report root-relative posix ``path:line`` sites)."""
    file_part, _, line = site.rpartition(":")
    try:
        rel = os.path.relpath(file_part, root)
    except ValueError:  # different drive (windows): keep absolute
        rel = file_part
    return f"{rel.replace(os.sep, '/')}:{line}"


def load_lock_witness(path: str, root: str) -> dict:
    """Parse a ``utils/lockorder.py`` witness file into the structure the
    LDT1001 rule consumes: ``{"edges": {(src, dst), ...}, "acquired":
    {site: count}}`` with sites relativized to ``root`` (``path:line``)."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    edges = {
        (_rel_site(e["src"], root), _rel_site(e["dst"], root))
        for e in data.get("edges", [])
    }
    acquired = {
        _rel_site(site, root): count
        for site, count in data.get("acquired", {}).items()
    }
    return {"edges": edges, "acquired": acquired}


def load_wire_witness(path: str) -> dict:
    """Parse a ``utils/wiretrack.py`` witness file into the structure the
    LDT1403 rule consumes: ``{"frames": {msg_value: count}, "fields":
    {msg_value: {field: count}}, "versions": {msg_value: [v, ...]}}``.
    Message types are numeric on the wire — the protocol model maps them
    back to ``MSG_*`` names, so every key must parse as an int HERE
    (``str(int(k))`` normalizes and raises into the caller's
    unreadable-witness exit-2 path, never a mid-analysis traceback)."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {
        "frames": {
            str(int(k)): int(v)
            for k, v in data.get("frames", {}).items()
        },
        "fields": {
            str(int(k)): {str(field): int(n) for field, n in fields.items()}
            for k, fields in data.get("fields", {}).items()
        },
        "versions": {
            str(int(k)): sorted(int(v) for v in versions)
            for k, versions in data.get("versions", {}).items()
        },
    }


def load_leak_witness(path: str, root: str) -> dict:
    """Parse a ``utils/leaktrack.py`` witness file into the structure the
    LDT1201 rule consumes: ``{"sites": {"path:line": {"acquired": n,
    "released": n, "leaked": n}}}`` with sites relativized to ``root`` —
    the same join-key discipline as the lock witness."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    sites = {
        _rel_site(site, root): dict(entry)
        for site, entry in data.get("sites", {}).items()
    }
    return {"sites": sites}


def load_compile_witness(path: str, root: str) -> dict:
    """Parse a ``utils/compiletrack.py`` witness file into the structure the
    LDT1703 rule and the mesh model's receipt consume: ``{"compiles":
    {"path:line": {"calls": n, "compiles": n, "post_warmup": n}},
    "transfers": {"h2d"|"d2h": {"path:line": {"count": n, "bytes": n}}}}``
    with sites relativized to ``root`` — the same join-key discipline as
    the lock/leak witnesses. Every count must parse as an int HERE so a
    malformed file raises into the caller's unreadable-witness exit-2
    path, never a mid-analysis traceback."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    compiles = {
        _rel_site(site, root): {
            "calls": int(entry["calls"]),
            "compiles": int(entry["compiles"]),
            "post_warmup": int(entry["post_warmup"]),
        }
        for site, entry in data.get("compiles", {}).items()
    }
    transfers = {
        str(direction): {
            _rel_site(site, root): {
                "count": int(entry["count"]),
                "bytes": int(entry["bytes"]),
            }
            for site, entry in table.items()
        }
        for direction, table in data.get("transfers", {}).items()
    }
    return {"compiles": compiles, "transfers": transfers}


def check_main(argv: Optional[Sequence[str]] = None,
               out=None) -> int:
    """The ``ldt check`` entry point. Returns the process exit status."""
    args = build_check_parser().parse_args(
        list(argv) if argv is not None else None
    )
    out = out if out is not None else sys.stdout
    root = os.path.abspath(args.root)

    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            out.write(f"{rid}  {rule.name}: {rule.description}\n")
        return 0

    config = load_config(root)
    if args.paths:
        if args.update_baseline:
            # A partial scan must never rewrite the whole baseline: findings
            # in unscanned files would be silently un-grandfathered and the
            # next full run would fail on them.
            out.write(
                "ldt check: --update-baseline requires a full scan — drop "
                "the explicit paths\n"
            )
            return 2
        config.paths = list(args.paths)
    if args.lock_witness:
        try:
            config.lock_witness = load_lock_witness(args.lock_witness, root)
        except (OSError, ValueError, KeyError, TypeError,
                AttributeError) as exc:
            out.write(
                f"ldt check: unreadable lock witness "
                f"{args.lock_witness}: {exc}\n"
            )
            return 2
    if args.leak_witness:
        try:
            config.leak_witness = load_leak_witness(args.leak_witness, root)
        except (OSError, ValueError, KeyError, TypeError,
                AttributeError) as exc:
            out.write(
                f"ldt check: unreadable leak witness "
                f"{args.leak_witness}: {exc}\n"
            )
            return 2
    if args.wire_witness:
        try:
            config.wire_witness = load_wire_witness(args.wire_witness)
        except (OSError, ValueError, KeyError, TypeError,
                AttributeError) as exc:
            out.write(
                f"ldt check: unreadable wire witness "
                f"{args.wire_witness}: {exc}\n"
            )
            return 2
    if args.compile_witness:
        try:
            config.compile_witness = load_compile_witness(
                args.compile_witness, root
            )
        except (OSError, ValueError, KeyError, TypeError,
                AttributeError) as exc:
            out.write(
                f"ldt check: unreadable compile witness "
                f"{args.compile_witness}: {exc}\n"
            )
            return 2

    timing: dict = {}
    findings, modules, files_checked = analyze_project(
        root, config, timing=timing
    )
    by_path = {m.relpath: m for m in modules}
    if files_checked == 0:
        # Scanning nothing is a misconfiguration (wrong cwd, bad --root,
        # bad paths), not a clean result — a 0-file "pass" would silently
        # void the gate.
        out.write(
            f"ldt check: no files matched {config.paths} under {root} — "
            "run from the repo root or pass --root\n"
        )
        return 2

    baseline_path = os.path.join(root, config.baseline)
    if args.update_baseline:
        # Witness-pruned findings never enter the baseline: they are
        # evidence-contradicted, not grandfathered debt.
        solid = [f for f in findings if not f.witness_pruned]
        write_baseline(baseline_path, solid, root, modules)
        out.write(
            f"ldt check: baseline written to {config.baseline} "
            f"({len(solid)} finding{'s' if len(solid) != 1 else ''})\n"
        )
        return 0

    if args.no_baseline:
        new, old = list(findings), []
    else:
        baseline = load_baseline(baseline_path)
        new, old = split_new_findings(findings, baseline, root, modules)

    rules = all_rules()

    def family_of(rule_id: str) -> str:
        rule = rules.get(rule_id)
        return getattr(rule, "family", "general") if rule else "general"

    if args.as_json:
        def line_text_of(f):
            mod = by_path.get(f.path)
            return mod.line_text(f.line) if mod is not None else ""

        render_json(
            new, out, root=root, grandfathered=len(old),
            files_checked=files_checked, line_text_of=line_text_of,
            family_of=family_of, timing=timing,
        )
    else:
        render_text(
            new, out, grandfathered=len(old), files_checked=files_checked
        )
        summary = timing.get("leak_witness")
        if summary is not None:
            # The corroboration receipt the CI stage greps: runtime lease
            # evidence mapped onto the static ownership model's acquire
            # sites.
            out.write(
                f"ldt check: leak witness: {summary['matched_sites']}/"
                f"{summary['runtime_sites']} runtime sites match static "
                f"acquire sites, {summary['leaked_sites']} leaked\n"
            )
        wire_summary = timing.get("wire_witness")
        if wire_summary is not None:
            # Same receipt discipline for the wire witness: observed
            # (msg, field) traffic mapped onto the static payload schema.
            versions = wire_summary.get("versions_seen") or []
            suffix = (
                " (versions seen: "
                + ", ".join(str(v) for v in versions) + ")"
                if versions else ""
            )
            out.write(
                f"ldt check: wire witness: "
                f"{wire_summary['matched_fields']}/"
                f"{wire_summary['observed_fields']} observed (msg, field) "
                f"tuples match the static schema over "
                f"{wire_summary['frames']} frames{suffix}\n"
            )
        compile_summary = timing.get("compile_witness")
        if compile_summary is not None:
            # Same receipt discipline for the compile witness: runtime jit
            # sites mapped onto the static mesh model's def-site candidates,
            # plus the transfer-event totals the CI stage eyeballs.
            out.write(
                f"ldt check: compile witness: "
                f"{compile_summary['matched_sites']}/"
                f"{compile_summary['runtime_sites']} runtime jit sites "
                f"match static jit sites, "
                f"{compile_summary['recompiled_sites']} recompiled "
                f"post-warmup, {compile_summary['h2d_events']} H2D / "
                f"{compile_summary['d2h_events']} D2H transfer events\n"
            )
    return 1 if any(not f.witness_pruned for f in new) else 0


# -- ldt graph ---------------------------------------------------------------


def build_graph_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ldt graph",
        description="render the cross-module concurrency model (thread "
                    "roots, lock acquisitions, lock-order edges)",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to model (default: configured paths)")
    p.add_argument("--root", default=".",
                   help="repo root (config + relative paths)")
    p.add_argument("--dot", action="store_true",
                   help="Graphviz DOT on stdout (pipe through `dot -Tsvg`)"
                        " instead of the text summary")
    p.add_argument("--ownership", action="store_true",
                   help="also render the resource-ownership model: "
                        "resource kinds as diamond nodes beside the "
                        "thread boxes and lock ellipses, acquire->release "
                        "edges, RED acquire edges for leak-on-path "
                        "findings")
    p.add_argument("--protocol", action="store_true",
                   help="also render the wire-protocol model: MSG_* "
                        "hexagons with writer->msg->reader edges, "
                        "per-message field schemas, and the version-gate "
                        "annotations LDT1402 enforces")
    p.add_argument("--loader", action="store_true",
                   help="also render the unified loader graph "
                        "(data/graph.py): the five canonical LoaderGraph "
                        "shapes as node chains, with cursor owners and "
                        "tunable-bearing nodes marked")
    p.add_argument("--mesh", action="store_true",
                   help="also render the device-semantics model "
                        "(analysis/meshmodel.py): jitted kernels with "
                        "their static/donated argument sets, and every "
                        "literal mesh-axis reference grouped per axis")
    return p


def _short(key: str) -> str:
    parts = key.split(".")
    return ".".join(parts[-2:]) if len(parts) >= 2 else key


def graph_main(argv: Optional[Sequence[str]] = None, out=None) -> int:
    """The ``ldt graph`` entry point. Returns the process exit status."""
    args = build_graph_parser().parse_args(
        list(argv) if argv is not None else None
    )
    out = out if out is not None else sys.stdout
    root = os.path.abspath(args.root)
    config = load_config(root)
    if args.paths:
        config.paths = list(args.paths)
    # Parse only — the graph needs the module set for the concurrency
    # model, not a full lint pass over every rule.
    modules, _parse_findings, files_checked = parse_modules(root, config)
    if files_checked == 0:
        out.write(
            f"ldt graph: no files matched {config.paths} under {root}\n"
        )
        return 2
    from .concmodel import build_program

    program = build_program(modules, config)
    owner = None
    if args.ownership:
        from .ownermodel import build_owner_model

        owner = build_owner_model(program, config)
    proto = None
    if args.protocol:
        from .protomodel import build_proto_model

        proto = build_proto_model(program, config)
    mesh = None
    if args.mesh:
        from .meshmodel import build_mesh_model

        mesh = build_mesh_model(program, config)
    loaders = None
    if args.loader:
        # Spec-only canonical graphs: describe() never compiles, so this
        # touches no dataset, socket, or decoder.
        from ..data.graph import canonical_graphs

        loaders = {
            name: g.describe() for name, g in canonical_graphs().items()
        }

    # thread root -> set of lock keys any function on that root acquires
    root_locks: dict = {}
    spawn_targets = sorted(
        {t for t, _m, _n in program.spawn_sites if t is not None}
    )
    for target in spawn_targets:
        locks = set()
        for fn in program.functions.values():
            if target in fn.roots:
                locks |= {lk for lk, _n in fn.acquires}
        root_locks[target] = locks

    if args.dot:
        out.write("digraph ldt_concurrency {\n")
        out.write("  rankdir=LR;\n")
        out.write('  node [fontname="monospace", fontsize=10];\n')
        for target in spawn_targets:
            out.write(
                f'  "thread:{target}" [label="{_short(target)}", '
                'shape=box, style=filled, fillcolor="#dbeafe"];\n'
            )
        for key in sorted(program.locks):
            out.write(
                f'  "lock:{key}" [label="{_short(key)}", shape=ellipse, '
                'style=filled, fillcolor="#fef3c7"];\n'
            )
        for target in spawn_targets:
            for lk in sorted(root_locks[target]):
                out.write(
                    f'  "thread:{target}" -> "lock:{lk}" '
                    '[color="#64748b"];\n'
                )
        seen = set()
        for e in program.lock_edges:
            if (e.src, e.dst) in seen:
                continue
            seen.add((e.src, e.dst))
            out.write(
                f'  "lock:{e.src}" -> "lock:{e.dst}" '
                f'[color="#dc2626", penwidth=2, '
                f'label="{e.module}:{e.line}"];\n'
            )
        if owner is not None:
            # Resource diamonds beside the thread boxes and lock ellipses:
            # function --acquire--> resource (RED when that acquire site
            # has a leak-on-path finding), resource --release--> kind's
            # release verbs.
            kinds = sorted({r.kind for r in owner.records})
            for kind in kinds:
                spec = owner.spec(kind)
                out.write(
                    f'  "res:{kind}" [label="{spec.describe or kind}", '
                    'shape=diamond, style=filled, fillcolor="#dcfce7"];\n'
                )
                out.write(
                    f'  "rel:{kind}" [label="release: '
                    f'{", ".join(spec.release)}", shape=plaintext];\n'
                )
                out.write(f'  "res:{kind}" -> "rel:{kind}" '
                          '[style=dashed, color="#16a34a"];\n')
            seen_acq = set()
            for rec in owner.records:
                key = (rec.func, rec.kind, rec.leak is not None)
                if key in seen_acq:
                    continue
                seen_acq.add(key)
                out.write(
                    f'  "fn:{rec.func}" [label="{_short(rec.func)}", '
                    'shape=box];\n'
                )
                if rec.leak is not None:
                    out.write(
                        f'  "fn:{rec.func}" -> "res:{rec.kind}" '
                        f'[color="#dc2626", penwidth=2, '
                        f'label="LEAK {rec.module}:{rec.line}"];\n'
                    )
                else:
                    out.write(
                        f'  "fn:{rec.func}" -> "res:{rec.kind}" '
                        f'[color="#16a34a", '
                        f'label="{rec.module}:{rec.line}"];\n'
                    )
        if proto is not None:
            # Message hexagons between their writers and readers: the
            # per-field schema rides the node label, gated fields marked.
            for name in sorted(proto.messages):
                info = proto.messages[name]
                if name in proto.binary_messages:
                    label = f"{name}\\n(binary)"
                else:
                    fields = sorted(set(info.writes) | set(info.reads))
                    marked = [
                        f + "*" if (
                            f"{name}.{f}" in proto.gated_fields
                            or f in proto.gated_fields
                        ) else f
                        for f in fields
                    ]
                    label = name + (
                        "\\n" + ", ".join(marked) if marked else ""
                    )
                out.write(
                    f'  "msg:{name}" [label="{label}", shape=hexagon, '
                    'style=filled, fillcolor="#ede9fe"];\n'
                )
                writers = sorted({
                    s.func for sites in info.writes.values()
                    for s in sites
                })
                readers = sorted({
                    s.func for sites in info.reads.values() for s in sites
                })
                for w in writers:
                    out.write(
                        f'  "fn:{w}" [label="{_short(w)}", shape=box];\n'
                        f'  "fn:{w}" -> "msg:{name}" '
                        '[color="#7c3aed"];\n'
                    )
                for r in readers:
                    out.write(
                        f'  "fn:{r}" [label="{_short(r)}", shape=box];\n'
                        f'  "msg:{name}" -> "fn:{r}" '
                        '[color="#2563eb"];\n'
                    )
        if mesh is not None:
            # Jitted kernels as double-octagon nodes (static/donated args in
            # the label), mesh axes as filled circles, axis-reference edges
            # labelled with their context; undeclared axes render RED.
            declared = set(mesh.mesh_axes)
            for axis in sorted(
                declared | {r.axis for r in mesh.axis_refs}
            ):
                color = "#fee2e2" if axis not in declared else "#cffafe"
                out.write(
                    f'  "axis:{axis}" [label="{axis}", shape=circle, '
                    f'style=filled, fillcolor="{color}"];\n'
                )
            for i, site in enumerate(mesh.jit_sites):
                label = f"{site.kind} {site.name}"
                if site.static_argnames or site.static_argnums:
                    statics = list(site.static_argnames) + [
                        f"#{n}" for n in site.static_argnums
                    ]
                    label += "\\nstatic: " + ", ".join(statics)
                if site.donate_argnums:
                    label += "\\ndonate: " + ", ".join(
                        f"#{n}" for n in site.donate_argnums
                    )
                    if site.donate_conditional:
                        label += " (conditional)"
                out.write(
                    f'  "jit:{i}" [label="{label}\\n'
                    f'{site.module}:{site.line}", shape=doubleoctagon, '
                    'style=filled, fillcolor="#fde68a"];\n'
                )
            by_axis: dict = {}
            for ref in mesh.axis_refs:
                by_axis.setdefault(ref.axis, []).append(ref)
            for axis, refs in sorted(by_axis.items()):
                contexts = sorted({r.context for r in refs})
                out.write(
                    f'  "axisrefs:{axis}" [label="{len(refs)} refs\\n'
                    f'{", ".join(contexts)}", shape=plaintext];\n'
                    f'  "axisrefs:{axis}" -> "axis:{axis}" '
                    '[style=dashed, color="#0891b2"];\n'
                )
        if loaders is not None:
            # One cluster per canonical shape: the node chain left to
            # right, cursor owner double-bordered, tunable bearers dashed.
            for shape, desc in loaders.items():
                cid = shape.replace("-", "_")
                out.write(f'  subgraph "cluster_loader_{cid}" {{\n')
                out.write(f'    label="loader: {shape}";\n')
                prev = None
                for i, node in enumerate(desc["nodes"]):
                    nid = f"ldr:{shape}:{i}"
                    label = node["node"]
                    if node["detail"]:
                        label += "\\n" + node["detail"]
                    if node["tunables"]:
                        label += "\\ntunables: " + ", ".join(
                            node["tunables"]
                        )
                    style = "filled"
                    if node["cursor"]:
                        label += "\\n[cursor owner]"
                    if node["tunables"]:
                        style += ",dashed"
                    peripheries = 2 if node["cursor"] else 1
                    out.write(
                        f'    "{nid}" [label="{label}", shape=box, '
                        f'style="{style}", fillcolor="#f1f5f9", '
                        f'peripheries={peripheries}];\n'
                    )
                    if prev is not None:
                        out.write(f'    "{prev}" -> "{nid}";\n')
                    prev = nid
                out.write("  }\n")
        out.write("}\n")
    else:
        out.write(f"concurrency model over {files_checked} files: "
                  f"{len(program.functions)} functions, "
                  f"{len(spawn_targets)} thread roots, "
                  f"{len(program.locks)} locks, "
                  f"{len(program.lock_edges)} lock-order edges\n")
        for target in spawn_targets:
            on_root = sum(
                1 for fn in program.functions.values()
                if target in fn.roots
            )
            locks = ", ".join(sorted(_short(k) for k in root_locks[target]))
            out.write(f"  thread {_short(target)}: {on_root} functions"
                      f"{' — locks: ' + locks if locks else ''}\n")
        seen = set()
        for e in program.lock_edges:
            if (e.src, e.dst) in seen:
                continue
            seen.add((e.src, e.dst))
            out.write(f"  order {_short(e.src)} -> {_short(e.dst)} "
                      f"({e.module}:{e.line}, {e.via})\n")
        cycles = program.lock_cycles()
        out.write(f"  lock-order cycles: {len(cycles)}\n")
        if owner is not None:
            leaks = [r for r in owner.records if r.leak is not None]
            out.write(
                f"  ownership model: {len(owner.records)} acquire sites "
                f"across {len({r.kind for r in owner.records})} resource "
                f"kinds, {len(leaks)} leak-on-path\n"
            )
            for rec in owner.records:
                tag = f"  LEAK({rec.leak})" if rec.leak is not None else ""
                out.write(
                    f"  resource {rec.kind} acquired in "
                    f"{_short(rec.func)} ({rec.module}:{rec.line}){tag}\n"
                )
        if proto is not None:
            n_fields = sum(
                len(set(i.writes) | set(i.reads))
                for i in proto.messages.values()
            )
            out.write(
                f"  protocol model: {len(proto.messages)} messages, "
                f"{n_fields} payload fields, "
                f"{len(proto.gate_constants)} version gates\n"
            )
            for name in sorted(proto.messages):
                info = proto.messages[name]
                if name in proto.binary_messages:
                    out.write(f"  msg {name}: binary payload\n")
                    continue
                fields = sorted(set(info.writes) | set(info.reads))
                if not fields:
                    continue
                parts = []
                for f in fields:
                    mark = ""
                    if f not in info.reads:
                        mark = "!w-only"  # written, no peer read (LDT1401)
                    elif f not in info.writes:
                        mark = "!r-only"  # read, no writer (LDT1403)
                    gate = proto.gated_fields.get(f"{name}.{f}") \
                        or proto.gated_fields.get(f)
                    if gate:
                        mark += f" >={gate}"
                    parts.append(f + (f" [{mark.strip()}]" if mark else ""))
                out.write(f"  msg {name}: {', '.join(parts)}\n")
        if mesh is not None:
            out.write(
                f"  mesh model: {len(mesh.jit_sites)} jit sites, "
                f"{len(mesh.axis_refs)} axis references over axes "
                f"({', '.join(mesh.mesh_axes)})\n"
            )
            for site in mesh.jit_sites:
                marks = []
                if site.static_argnames or site.static_argnums:
                    marks.append("static: " + ", ".join(
                        list(site.static_argnames)
                        + [f"#{n}" for n in site.static_argnums]
                    ))
                if site.donate_argnums:
                    don = "donate: " + ", ".join(
                        f"#{n}" for n in site.donate_argnums
                    )
                    if site.donate_conditional:
                        don += " (conditional)"
                    marks.append(don)
                tail = f" [{'; '.join(marks)}]" if marks else ""
                out.write(
                    f"  {site.kind} {site.name} "
                    f"({site.module}:{site.line}){tail}\n"
                )
            by_axis: dict = {}
            for ref in mesh.axis_refs:
                by_axis.setdefault(ref.axis, []).append(ref)
            declared = set(mesh.mesh_axes)
            for axis, refs in sorted(by_axis.items()):
                flag = "" if axis in declared else " [UNDECLARED]"
                out.write(
                    f"  axis {axis}{flag}: {len(refs)} references "
                    f"({', '.join(sorted({r.context for r in refs}))})\n"
                )
        if loaders is not None:
            out.write(
                f"  loader graph model (data/graph.py): {len(loaders)} "
                "canonical shapes; * = cursor owner, ~ = tunable-bearing\n"
            )
            for shape, desc in loaders.items():
                chain = " -> ".join(
                    n["node"]
                    + ("*" if n["cursor"] else "")
                    + ("~" if n["tunables"] else "")
                    for n in desc["nodes"]
                )
                out.write(f"  loader {shape}: {chain}\n")
                for n in desc["nodes"]:
                    marks = []
                    if n["cursor"]:
                        marks.append("cursor owner")
                    if n["tunables"]:
                        marks.append("tunables: " + ", ".join(n["tunables"]))
                    tail = f" [{'; '.join(marks)}]" if marks else ""
                    out.write(
                        f"    {n['kind']:<10} {n['node']}"
                        f"{' — ' + n['detail'] if n['detail'] else ''}"
                        f"{tail}\n"
                    )
    return 0


if __name__ == "__main__":
    raise SystemExit(check_main())
