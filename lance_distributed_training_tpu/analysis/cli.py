"""``ldt check`` — run the distributed-training lint over the repo.

Exit status is the gate contract: 0 when no NEW findings (relative to the
baseline, when one exists), 1 when new findings are reported, 2 on usage
errors. ``--update-baseline`` grandfathers the current findings so the gate
can be adopted incrementally and ratcheted down.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence

from .config import load_config
from .core import (
    all_rules,
    analyze_project,
    load_baseline,
    split_new_findings,
    write_baseline,
)
from .reporters import render_json, render_text

__all__ = ["check_main", "build_check_parser"]


def build_check_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ldt check",
        description="AST-based distributed-training lint "
                    "(rules LDT001-LDT601; config in [tool.ldt-check])",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to check (default: configured paths)")
    p.add_argument("--root", default=".",
                   help="repo root: config + baseline live here, reported "
                        "paths are relative to it")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--update-baseline", action="store_true",
                   help="write current findings to the baseline file and "
                        "exit 0 — future runs fail only on NEW findings")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding as new")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    return p


def check_main(argv: Optional[Sequence[str]] = None,
               out=None) -> int:
    """The ``ldt check`` entry point. Returns the process exit status."""
    args = build_check_parser().parse_args(
        list(argv) if argv is not None else None
    )
    out = out if out is not None else sys.stdout
    root = os.path.abspath(args.root)

    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            out.write(f"{rid}  {rule.name}: {rule.description}\n")
        return 0

    config = load_config(root)
    if args.paths:
        if args.update_baseline:
            # A partial scan must never rewrite the whole baseline: findings
            # in unscanned files would be silently un-grandfathered and the
            # next full run would fail on them.
            out.write(
                "ldt check: --update-baseline requires a full scan — drop "
                "the explicit paths\n"
            )
            return 2
        config.paths = list(args.paths)

    findings, modules, files_checked = analyze_project(root, config)
    by_path = {m.relpath: m for m in modules}
    if files_checked == 0:
        # Scanning nothing is a misconfiguration (wrong cwd, bad --root,
        # bad paths), not a clean result — a 0-file "pass" would silently
        # void the gate.
        out.write(
            f"ldt check: no files matched {config.paths} under {root} — "
            "run from the repo root or pass --root\n"
        )
        return 2

    baseline_path = os.path.join(root, config.baseline)
    if args.update_baseline:
        write_baseline(baseline_path, findings, root, modules)
        out.write(
            f"ldt check: baseline written to {config.baseline} "
            f"({len(findings)} finding{'s' if len(findings) != 1 else ''})\n"
        )
        return 0

    if args.no_baseline:
        new, old = list(findings), []
    else:
        baseline = load_baseline(baseline_path)
        new, old = split_new_findings(findings, baseline, root, modules)

    if args.as_json:
        def line_text_of(f):
            mod = by_path.get(f.path)
            return mod.line_text(f.line) if mod is not None else ""

        render_json(
            new, out, root=root, grandfathered=len(old),
            files_checked=files_checked, line_text_of=line_text_of,
        )
    else:
        render_text(
            new, out, grandfathered=len(old), files_checked=files_checked
        )
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(check_main())
