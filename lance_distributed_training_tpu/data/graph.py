"""Unified loader graph — the one composable op-graph behind every loader.

Five loader implementations grew side by side (``DataPipeline``,
``MapStylePipeline``, ``FolderDataPipeline``, ``RemoteLoader``,
``FleetLoader``), and every plane landed since — the batch cache (r13),
device-decode declarations (r12), the ragged token plane (r15) — had to be
wired five times plus the trainer. tf.data (PAPERS.md 2101.12127) made the
case that an input pipeline expressed as a graph of composable ops is what
makes transport, caching, and autotuning pluggable; the tf.data-service
follow-up (2210.14826) shows the same graph is the precondition for a
multi-tenant job plane. This module is that graph.

Vocabulary — typed nodes, one per concern:

* **Source** — what rows exist and in what order: :class:`LanceSource`
  (columnar fragments + sampler plan), :class:`MapStyleSource` (permuted
  row indices), :class:`FolderSource` (walk-ordered files),
  :class:`EvalSource` (full-coverage padded index plan). A source owns the
  *plan*: a pure function of (dataset, sampler, batch, shard, seed, epoch)
  — the property every resume cursor and cache key leans on.
* **Decode** — the single decode-boundary seam. In-process it carries the
  decode hook itself; behind a remote transport it carries only the
  *declaration* (task/image_size/seq_len/device_decode/token_pack) that
  rides the HELLO skew checks, because decode runs server-side.
* **Cache** — the r13 :class:`~.cache.BatchCache` plugged in AT the decode
  boundary (a hit skips read+decode and returns byte-identical pages).
* **Pool** / **Buffers** / **Prefetch** — decode worker processes, the
  shared :class:`~.buffers.BufferPool`, and the decoded-batch queue depth
  (+ producer thread count).
* **Transport** — where the stream crosses a process boundary:
  :class:`InProcess` (none), :class:`ServiceTransport` (one DataService),
  :class:`FleetTransport` (coordinator-striped fleet).
* **DevicePut** / **Place** — the synchronous H2D closure (control arm) or
  the r6 placement plane owning H2D on its own thread.

:class:`LoaderGraph` composes nodes into one loader with the contract every
consumer already speaks: ``__iter__``/``__len__``, ``state_dict``/
``load_state_dict`` (ONE resume cursor at the graph root, delegated to the
engine that owns it), ``set_prefetch``/``tunables()`` (one aggregation for
the r9 autotuner), plus attribute fallthrough for engine-specific surface
(``counters``, ``placement_counters``, ``num_classes``, ...).

Compilation is *lazy and cached*: ``describe()`` renders topology without
touching a dataset, socket, or decoder (the ``ldt graph --loader`` view),
while the first iteration/len/cursor call compiles the node set down to
exactly the engine assembly the legacy constructors produced — same plan
construction, same cache binding, same kwarg defaults — which is what makes
the graph path bit-identical to the pre-graph loaders (pinned by
``tests/test_graph.py``'s parity matrix).

The legacy classes remain the runtime engines beneath this module; the
factories (``make_train_pipeline``/``make_map_style_pipeline``/
``make_eval_pipeline``) and the trainer/server build paths compose graphs.
LDT1601 (graph-hygiene) keeps it that way: new source→decode→batch
compositions outside this module are findings, so the next plane cannot
regress to a sixth parallel loader.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

__all__ = [
    "Node",
    "Source",
    "LanceSource",
    "MapStyleSource",
    "FolderSource",
    "EvalSource",
    "Decode",
    "Cache",
    "Pool",
    "Buffers",
    "Prefetch",
    "Transport",
    "InProcess",
    "ServiceTransport",
    "FleetTransport",
    "DevicePut",
    "Place",
    "LoaderGraph",
    "canonical_graphs",
]


# -- node vocabulary --------------------------------------------------------


class Node:
    """One typed op in a :class:`LoaderGraph`.

    ``kind`` names the concern (one node per kind per graph); ``describe()``
    renders without compiling — no dataset open, no socket, no decoder
    import — so spec-only graphs (``dataset=None``) still draw topology.
    """

    kind = "node"
    #: knob names this node contributes to the graph root's ``tunables()``
    #: (informational — the compiled engines own the live Tunable objects).
    tunable_names: Sequence[str] = ()

    def detail(self) -> str:
        return ""

    def describe(self) -> dict:
        return {
            "node": type(self).__name__,
            "kind": self.kind,
            "detail": self.detail(),
            "tunables": list(self.tunable_names),
        }

    def __repr__(self) -> str:
        d = self.detail()
        return f"{type(self).__name__}({d})" if d else f"{type(self).__name__}()"


class Source(Node):
    kind = "source"


class LanceSource(Source):
    """Columnar fragments + sampler plan (the iterable arm's source).

    Owns plan construction: the ``full``-sampler multi-process refusal, the
    cross-process equal-step validation (the fragment-imbalance deadlock
    guard), and the :func:`~.samplers.make_plan` call — one home for logic
    that previously lived in ``make_train_pipeline`` AND the DataService.
    ``dataset=None`` is a spec-only source: it can describe itself, declare
    plan parameters + ``dataset_fingerprint`` to a remote transport (the
    server owns the real rows), but cannot build an in-process plan.
    """

    def __init__(
        self,
        dataset,
        sampler_type: str,
        batch_size: int,
        process_index: int,
        process_count: int,
        *,
        shuffle: bool = False,
        seed: int = 0,
        epoch: int = 0,
        check_deadlock: bool = True,
        dataset_fingerprint: Optional[str] = None,
    ):
        self.dataset = dataset
        self.sampler_type = sampler_type
        self.batch_size = int(batch_size)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.epoch = int(epoch)
        self.check_deadlock = bool(check_deadlock)
        self._fingerprint = dataset_fingerprint

    def detail(self) -> str:
        return (
            f"sampler={self.sampler_type} shard="
            f"{self.process_index}/{self.process_count} "
            f"seed={self.seed} epoch={self.epoch}"
            + ("" if self.dataset is not None else " [spec-only]")
        )

    @property
    def dataset_fingerprint(self) -> Optional[str]:
        if self._fingerprint is None and self.dataset is not None:
            self._fingerprint = self.dataset.fingerprint()
        return self._fingerprint

    def _refuse_full_multiprocess(self) -> None:
        if (
            self.sampler_type in ("full", "full_scan")
            and self.process_count > 1
        ):
            # FullScanSampler is "not DP-aware" — each process's identical
            # full scan stitched into a "global" batch would duplicate
            # every row; refuse instead of silently training on duplicates.
            raise ValueError(
                "sampler_type='full' is not DP-aware (every process scans "
                "the whole dataset) and cannot run across "
                f"{self.process_count} processes; use sampler_type='batch' "
                "or 'fragment', or launch a single process (no "
                "coordinator/multi-host env) for eval/debug"
            )

    def shard_plans(self) -> list:
        """Every process's plan, equal-step validated — the cross-shard
        collective-deadlock guard. Shared by the in-process compile and the
        DataService (which validates ALL shards even though training
        happens elsewhere)."""
        from .samplers import assert_equal_step_counts, make_plan

        rows = self.dataset.fragment_rows()
        plans = [
            make_plan(self.sampler_type, rows, self.batch_size, p,
                      self.process_count, shuffle=self.shuffle,
                      seed=self.seed, epoch=self.epoch)
            for p in range(self.process_count)
        ]
        if self.sampler_type not in ("full", "full_scan"):
            assert_equal_step_counts(plans, self.batch_size)
        return plans

    def plan(self):
        """THIS shard's epoch plan — a pure function of (dataset, sampler,
        batch, shard, seed, epoch)."""
        if self.dataset is None:
            raise ValueError(
                "spec-only LanceSource (dataset=None) cannot build an "
                "in-process plan; attach a ServiceTransport/FleetTransport "
                "or construct with a dataset"
            )
        self._refuse_full_multiprocess()
        if (
            self.check_deadlock
            and self.sampler_type not in ("full", "full_scan")
        ):
            return self.shard_plans()[self.process_index]
        from .samplers import make_plan

        return make_plan(
            self.sampler_type, self.dataset.fragment_rows(),
            self.batch_size, self.process_index, self.process_count,
            shuffle=self.shuffle, seed=self.seed, epoch=self.epoch,
        )


class MapStyleSource(Source):
    """Permuted row indices (``DistributedSampler`` semantics), optionally
    restricted to a filter's ``index_pool``."""

    def __init__(
        self,
        dataset,
        batch_size: int,
        process_index: int,
        process_count: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        epoch: int = 0,
        drop_last: bool = True,
        index_pool=None,
    ):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.epoch = int(epoch)
        self.drop_last = bool(drop_last)
        self.index_pool = index_pool

    def detail(self) -> str:
        pool = "" if self.index_pool is None else (
            f" pool={len(self.index_pool)}rows"
        )
        return (
            f"shard={self.process_index}/{self.process_count} "
            f"shuffle={self.shuffle} seed={self.seed} "
            f"epoch={self.epoch}{pool}"
        )


class FolderSource(Source):
    """Walk-ordered image-folder tree (the file-based control arm)."""

    def __init__(
        self,
        root: Optional[str],
        batch_size: int,
        process_index: int,
        process_count: int,
        *,
        loader_style: str = "map",
        shuffle: bool = True,
        seed: int = 0,
        epoch: int = 0,
        drop_last: bool = True,
        dataset_fingerprint: Optional[str] = None,
    ):
        self.root = root
        self.batch_size = int(batch_size)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.loader_style = loader_style
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.epoch = int(epoch)
        self.drop_last = bool(drop_last)
        self.dataset_fingerprint = dataset_fingerprint

    def detail(self) -> str:
        return (
            f"style={self.loader_style} shard="
            f"{self.process_index}/{self.process_count} "
            f"seed={self.seed} epoch={self.epoch}"
            + ("" if self.root is not None else " [spec-only]")
        )


class EvalSource(Source):
    """Full-coverage eval plan: every row exactly once, the ragged tail
    padded back to a full global batch by wrap-around rows carried with
    ``_weight`` 0.0 — one compiled shape, equal steps on every process.
    ``read_fn`` maps an index array to an Arrow table (``Dataset.take`` for
    the columnar arm, the file reader for the folder arm), so both storage
    arms share this source."""

    def __init__(
        self,
        read_fn: Optional[Callable],
        num_rows: int,
        global_batch: int,
        process_index: int,
        process_count: int,
        *,
        index_pool=None,
    ):
        self.read_fn = read_fn
        self.num_rows = int(num_rows)
        self.global_batch = int(global_batch)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.index_pool = index_pool

    def detail(self) -> str:
        total = (
            self.num_rows if self.index_pool is None
            else len(self.index_pool)
        )
        return (
            f"rows={total} global_batch={self.global_batch} "
            f"shard={self.process_index}/{self.process_count} "
            "padded-tail"
        )

    def plan(self):
        from .samplers import padded_eval_index_batches

        total = (
            self.num_rows if self.index_pool is None
            else len(self.index_pool)
        )
        return padded_eval_index_batches(
            total, self.global_batch, self.process_index,
            self.process_count, index_pool=self.index_pool,
        )


class Decode(Node):
    """The decode-boundary seam — where cache and device-decode plug in.

    In-process graphs carry the decode hook itself (``decode_fn``: Arrow
    table → dict of host arrays). Remote graphs carry ``decode_fn=None``
    plus the *declaration* kwargs: the server owns the decoder, and the
    declarations ride the HELLO handshake's skew checks so a
    differently-configured server is rejected at connect time, never
    mid-epoch.

    ``schedule`` attaches straggler-aware dispatch at the decode seam
    (worker-pool graphs only): a :class:`~.schedule.DecodeScheduler`, a
    dict of its options (``{"lookahead": 8, "heavy_share": 25}``), or
    ``True`` for defaults — compile builds the scheduler with a
    :meth:`~.schedule.CostModel.from_env` warm-started cost model, so a
    restarted job schedules from its ``LDT_COST_PATH`` history. Remote
    graphs refuse it: the server owns dispatch
    (``ServeConfig.sched_lookahead``/``sched_heavy_share``).
    """

    kind = "decode"
    tunable_names = ("coeff_chunk",)

    def __init__(
        self,
        decode_fn: Optional[Callable] = None,
        *,
        columns: Optional[Sequence[str]] = None,
        task_type: Optional[str] = None,
        image_size: Optional[int] = None,
        seq_len: Optional[int] = None,
        device_decode: Optional[bool] = None,
        token_pack: Optional[bool] = None,
        schedule=None,
    ):
        self.decode_fn = decode_fn
        self.columns = columns
        self.task_type = task_type
        self.image_size = image_size
        self.seq_len = seq_len
        self.device_decode = device_decode
        self.token_pack = token_pack
        self.schedule = schedule
        if schedule is not None:
            # Instance override (the class default stays unchanged so
            # schedule-less graphs — including every canonical describe
            # golden — render exactly as before).
            self.tunable_names = (
                "coeff_chunk", "sched_lookahead", "sched_heavy_share",
            )

    def detail(self) -> str:
        sched = "" if self.schedule is None else " sched=on"
        if self.decode_fn is not None:
            name = getattr(
                type(self.decode_fn), "__name__", str(self.decode_fn)
            )
            cols = (
                "" if self.columns is None
                else f" columns={list(self.columns)}"
            )
            return f"fn={name}{cols}{sched}"
        declared = [
            f"{k}={v}"
            for k, v in (
                ("task", self.task_type), ("image_size", self.image_size),
                ("seq_len", self.seq_len),
                ("device_decode", self.device_decode),
                ("token_pack", self.token_pack),
            )
            if v is not None
        ]
        return (
            "server-side [" + " ".join(declared) + "]"
            if declared else "server-side"
        )


class Cache(Node):
    """The r13 decoded-batch cache bound at the decode boundary: a hit is
    byte-identical to what decode would have produced, in fresh pool-leased
    pages. ``batch_cache=None`` keeps the node as a documented seam with
    the exact cacheless behavior. ``dataset_fingerprint`` overrides the
    source's content identity (the eval arm's injected fingerprint)."""

    kind = "cache"

    def __init__(self, batch_cache=None, *,
                 dataset_fingerprint: Optional[str] = None):
        self.batch_cache = batch_cache
        self.dataset_fingerprint = dataset_fingerprint

    def detail(self) -> str:
        return "on" if self.batch_cache is not None else "off"


class Pool(Node):
    """Decode worker-process pool (``num_workers`` parity); ``None`` runs
    decode on the producer thread + the native decoder's own threads."""

    kind = "pool"
    tunable_names = ("workers",)

    def __init__(self, workers=None):
        self.workers = workers

    def detail(self) -> str:
        return "producer-thread" if self.workers is None else "worker-pool"


class Buffers(Node):
    """The shared :class:`~.buffers.BufferPool` — decoders lease output
    pages, the consumer side releases them after device_put dispatch (or
    post-yield for host batches), so pages recycle across batches."""

    kind = "buffers"
    tunable_names = ("pool_pages",)

    def __init__(self, pool=None):
        self.pool = pool

    def detail(self) -> str:
        return "pooled" if self.pool is not None else "unpooled"


class Prefetch(Node):
    """Decoded-batch queue depth ahead of the consumer + producer thread
    count (results stay in plan order)."""

    kind = "prefetch"
    tunable_names = ("prefetch",)

    def __init__(self, depth: int = 2, *, producers: int = 1):
        self.depth = int(depth)
        self.producers = int(producers)

    def detail(self) -> str:
        return f"depth={self.depth} producers={self.producers}"


class Transport(Node):
    kind = "transport"


class InProcess(Transport):
    """No process boundary: source→decode→batch runs in this process."""

    def detail(self) -> str:
        return "in-process"


class ServiceTransport(Transport):
    """One remote DataService: plan + decode run server-side, this process
    streams length-prefixed host batches. Network knobs
    (``connect_retries``/``backoff_s``/``timeout_s``/``registry``) pass
    through to :class:`~..service.client.RemoteLoader` verbatim, so its
    defaults stay the single source of truth. ``job_id``/``job_priority``
    (v6 job plane) declare this stream's tenancy — explicit so
    ``describe()`` can show it; they fold into the same pass-through."""

    def __init__(self, addr: str, job_id: Optional[str] = None,
                 job_priority: Optional[str] = None, **opts):
        self.addr = addr
        if job_id is not None:
            opts["job_id"] = job_id
            if job_priority is not None:
                opts["job_priority"] = job_priority
        self.opts = opts

    def detail(self) -> str:
        job = self.opts.get("job_id")
        suffix = f" job={job}" if job else ""
        return f"service addr={self.addr}{suffix}"


class FleetTransport(Transport):
    """Coordinator-striped fleet of DataServices: batches round-robin
    across the member stripe, merged back into plan order client-side.
    Extra knobs (``resolve_retries``/``stripe_queue_depth``/
    ``exclusion_ttl_s``/...) pass through to
    :class:`~..fleet.balancer.FleetLoader` verbatim."""

    tunable_names = ("stripe_width",)

    def __init__(self, coordinator_addr: str, job_id: Optional[str] = None,
                 job_priority: Optional[str] = None, **opts):
        self.coordinator_addr = coordinator_addr
        if job_id is not None:
            opts["job_id"] = job_id
            if job_priority is not None:
                opts["job_priority"] = job_priority
        self.opts = opts

    def detail(self) -> str:
        job = self.opts.get("job_id")
        suffix = f" job={job}" if job else ""
        return f"fleet coordinator={self.coordinator_addr}{suffix}"


class DevicePut(Node):
    """Synchronous H2D closure on the consumer thread (the control arm);
    ``fn=None`` yields host batches — the default since r7, where
    :class:`Place` owns H2D downstream."""

    kind = "device_put"

    def __init__(self, fn: Optional[Callable] = None):
        self.fn = fn

    def detail(self) -> str:
        return "sync-closure" if self.fn is not None else "host-batches"


class Place(Node):
    """The r6 placement plane: a ring of in-flight device batches placed by
    a dedicated H2D thread; owns the consumed-batch cursor when present."""

    kind = "place"
    tunable_names = ("ring_depth",)

    def __init__(self, plane=None):
        self.plane = plane

    def detail(self) -> str:
        if self.plane is None:
            return "plane"
        return f"ring_depth={getattr(self.plane, 'depth', '?')}"


# -- the graph --------------------------------------------------------------

_SINGLETON_KINDS = (
    "source", "decode", "cache", "pool", "buffers", "prefetch",
    "transport", "device_put", "place",
)


class LoaderGraph:
    """A composed loader: typed nodes in, the standard loader contract out.

    Topology rules (validated at construction): exactly one ``source``
    node, at most one node of every other kind, and a remote transport
    excludes the in-process-only nodes (``Cache``/``Pool`` — the server
    owns cache and workers — and an in-process ``decode_fn``).

    ``compile()`` lowers the node set to the matching engine exactly once
    (cached); ``describe()`` never compiles. The resume cursor, the
    tunables aggregation, and iteration all delegate to the compiled
    engine, so a graph is drop-in wherever a legacy loader was.
    """

    def __init__(self, *nodes: Node):
        by_kind: dict = {}
        for node in nodes:
            if not isinstance(node, Node):
                raise TypeError(f"not a graph node: {node!r}")
            if node.kind in by_kind:
                raise ValueError(
                    f"duplicate {node.kind!r} node: {node!r} vs "
                    f"{by_kind[node.kind]!r}"
                )
            if node.kind not in _SINGLETON_KINDS:
                raise ValueError(f"unknown node kind {node.kind!r}")
            by_kind[node.kind] = node
        if "source" not in by_kind:
            raise ValueError("a LoaderGraph needs exactly one Source node")
        self.nodes = list(nodes)
        self._by_kind = by_kind
        self._validate()
        self._runtime = None
        # The engine beneath a Place wrap (same object as _runtime when no
        # Place node): __getattr__ falls back here for engine-only surface
        # (num_classes, counters) the placement wrapper does not re-export.
        self._engine = None
        # Resume cursor staged before compile (applied by compile());
        # afterwards the engine owns it and this stays None.
        self._pending_state: Optional[dict] = None

    # -- topology ----------------------------------------------------------

    def node(self, kind: str) -> Optional[Node]:
        return self._by_kind.get(kind)

    @property
    def source(self) -> Source:
        return self._by_kind["source"]

    @property
    def transport(self) -> Transport:
        return self._by_kind.get("transport") or InProcess()

    def _validate(self) -> None:
        src = self.source
        transport = self.transport
        decode = self.node("decode")
        remote = isinstance(transport, (ServiceTransport, FleetTransport))
        if remote:
            if not isinstance(src, LanceSource):
                raise ValueError(
                    f"{type(transport).__name__} streams a server-side "
                    "lance plan; the source must be a LanceSource "
                    f"(spec-only is fine), got {type(src).__name__}"
                )
            if decode is not None and decode.decode_fn is not None:
                raise ValueError(
                    "remote transports decode server-side: Decode must be "
                    "declaration-only (decode_fn=None, with task_type/"
                    "image_size/... riding the HELLO skew checks)"
                )
            if decode is not None and decode.schedule is not None:
                raise ValueError(
                    "remote transports dispatch server-side: drop "
                    "schedule= from Decode and configure the DataService "
                    "(ServeConfig.sched_lookahead / sched_heavy_share) "
                    "instead"
                )
            for kind in ("cache", "pool"):
                node = self.node(kind)
                payload = getattr(node, "batch_cache", None) or getattr(
                    node, "workers", None
                )
                if node is not None and payload is not None:
                    raise ValueError(
                        f"a {kind!r} node cannot ride a remote transport — "
                        "the DataService owns cache and decode workers "
                        "server-side (ServeConfig)"
                    )
        else:
            if decode is None or decode.decode_fn is None:
                raise ValueError(
                    "in-process graphs need a Decode node with a decode_fn"
                )
            if isinstance(src, EvalSource):
                pool = self.node("pool")
                if pool is not None and pool.workers is not None:
                    raise ValueError(
                        "EvalSource runs decode on producer threads (a "
                        "single pass needs no worker-pool protocol); drop "
                        "the Pool node"
                    )

    # -- compilation -------------------------------------------------------

    def compile(self):
        """Lower to the engine assembly (cached). Compilation happens on
        the constructing thread before the loader is shared; afterwards
        every delegate reads the same immutable reference."""
        if self._runtime is None:
            self._runtime = self._build()
            if self._pending_state is not None:
                self._runtime.load_state_dict(self._pending_state)
                self._pending_state = None
        return self._runtime

    def _build(self):
        transport = self.transport
        if isinstance(transport, (ServiceTransport, FleetTransport)):
            engine = self._build_remote(transport)
        else:
            src = self.source
            if isinstance(src, LanceSource):
                engine = self._build_lance(src)
            elif isinstance(src, MapStyleSource):
                engine = self._build_map_style(src)
            elif isinstance(src, FolderSource):
                engine = self._build_folder(src)
            elif isinstance(src, EvalSource):
                engine = self._build_eval(src)
            else:
                raise ValueError(f"unbuildable source {type(src).__name__}")
        self._engine = engine
        place = self.node("place")
        if place is not None:
            if place.plane is None:
                raise ValueError(
                    "Place node has no plane — construct with "
                    "Place(PlacementPlane(mesh, ...))"
                )
            engine = place.plane.wrap(engine)
        return engine

    def _common(self) -> dict:
        """The knobs every in-process engine shares, node defaults matching
        the legacy constructor defaults exactly."""
        decode = self.node("decode")
        prefetch = self.node("prefetch") or Prefetch()
        pool = self.node("pool") or Pool()
        buffers = self.node("buffers") or Buffers()
        put = self.node("device_put") or DevicePut()
        cache = self.node("cache") or Cache()
        return {
            "decode_fn": decode.decode_fn,
            "columns": decode.columns,
            "device_put_fn": put.fn,
            "prefetch": prefetch.depth,
            "producers": prefetch.producers,
            "workers": pool.workers,
            "buffer_pool": buffers.pool,
            "batch_cache": cache.batch_cache,
            "scheduler": self._scheduler(decode),
        }

    @staticmethod
    def _scheduler(decode):
        """Lower the Decode node's ``schedule`` spec to a live
        :class:`~.schedule.DecodeScheduler` (instances pass through;
        dicts/``True`` build one, warm-started from ``LDT_COST_PATH`` —
        the restart-schedules-from-history wiring)."""
        spec = getattr(decode, "schedule", None)
        if spec is None:
            return None
        from .schedule import CostModel, DecodeScheduler

        if isinstance(spec, DecodeScheduler):
            return spec
        opts = {} if spec is True else dict(spec)
        return DecodeScheduler(CostModel.from_env(), **opts)

    def _build_lance(self, src: LanceSource):
        from .cache import PlanCache, decode_fingerprint, plan_fingerprint
        from .pipeline import DataPipeline, _range_read, _with_columns

        c = self._common()
        plan = src.plan()
        plan_cache = None
        if c["batch_cache"] is not None:
            # Item-content keys make the binding epoch-coherent by
            # construction: epoch e's plan items that replay epoch 0's
            # rows hash to the SAME keys regardless of step position.
            cols = list(c["columns"]) if c["columns"] is not None else None
            decode_fn = c["decode_fn"]
            plan_cache = PlanCache(
                c["batch_cache"],
                src.dataset.fingerprint(),
                # Callable: evaluated per key, so a live decoder actuation
                # (coeff_chunk) re-scopes later entries without aliasing.
                lambda: plan_fingerprint(
                    decode=decode_fingerprint(decode_fn), columns=cols,
                ),
            )
        return DataPipeline(
            src.dataset, plan, c["decode_fn"], c["device_put_fn"],
            c["prefetch"],
            read_fn=_with_columns(_range_read, c["columns"]),
            workers=c["workers"], producers=c["producers"],
            buffer_pool=c["buffer_pool"], plan_cache=plan_cache,
            scheduler=c["scheduler"],
        )

    def _build_map_style(self, src: MapStyleSource):
        from .pipeline import MapStylePipeline

        c = self._common()
        return MapStylePipeline(
            src.dataset, src.batch_size, src.process_index,
            src.process_count, c["decode_fn"], c["device_put_fn"],
            shuffle=src.shuffle, seed=src.seed, epoch=src.epoch,
            drop_last=src.drop_last, prefetch=c["prefetch"],
            workers=c["workers"], producers=c["producers"],
            columns=c["columns"], index_pool=src.index_pool,
            buffer_pool=c["buffer_pool"], batch_cache=c["batch_cache"],
            scheduler=c["scheduler"],
        )

    def _build_folder(self, src: FolderSource):
        from .folder import FolderDataPipeline

        if src.root is None:
            raise ValueError(
                "spec-only FolderSource (root=None) cannot compile"
            )
        c = self._common()
        return FolderDataPipeline(
            src.root, src.batch_size, src.process_index,
            src.process_count, c["decode_fn"], c["device_put_fn"],
            loader_style=src.loader_style, shuffle=src.shuffle,
            seed=src.seed, epoch=src.epoch, drop_last=src.drop_last,
            prefetch=c["prefetch"], workers=c["workers"],
            producers=c["producers"], buffer_pool=c["buffer_pool"],
            batch_cache=c["batch_cache"],
            dataset_fingerprint=src.dataset_fingerprint,
            scheduler=c["scheduler"],
        )

    def _build_eval(self, src: EvalSource):
        from .cache import PlanCache, decode_fingerprint, plan_fingerprint
        from .pipeline import DataPipeline

        c = self._common()
        cache = self.node("cache") or Cache()
        if src.read_fn is None:
            raise ValueError("spec-only EvalSource (read_fn=None) cannot "
                             "compile")
        plan = src.plan()
        decode_fn = c["decode_fn"]
        read_fn = src.read_fn

        def _read(_ds, entry):
            idx, weights = entry
            return read_fn(idx), weights

        def _decode(payload):
            table, weights = payload
            out = dict(decode_fn(table))
            out["_weight"] = weights
            return out

        plan_cache = None
        if (
            cache.batch_cache is not None
            and cache.dataset_fingerprint is not None
        ):
            # eval=1 scope: eval entries carry _weight, so they must
            # never alias train entries over the same rows.
            plan_cache = PlanCache(
                cache.batch_cache,
                cache.dataset_fingerprint,
                lambda: plan_fingerprint(
                    decode=decode_fingerprint(decode_fn), eval=1,
                ),
            )
        return DataPipeline(
            None, plan, _decode, c["device_put_fn"], c["prefetch"],
            read_fn=_read, producers=c["producers"],
            buffer_pool=c["buffer_pool"], plan_cache=plan_cache,
        )

    def _build_remote(self, transport: Transport):
        src = self.source
        decode = self.node("decode") or Decode()
        prefetch = self.node("prefetch") or Prefetch()
        buffers = self.node("buffers") or Buffers()
        put = self.node("device_put") or DevicePut()
        common = dict(
            sampler_type=src.sampler_type,
            shuffle=src.shuffle,
            seed=src.seed,
            epoch=src.epoch,
            prefetch=prefetch.depth,
            columns=decode.columns,
            task_type=decode.task_type,
            image_size=decode.image_size,
            seq_len=decode.seq_len,
            device_decode=decode.device_decode,
            token_pack=decode.token_pack,
            dataset_fingerprint=src.dataset_fingerprint,
            buffer_pool=buffers.pool,
        )
        common.update(transport.opts)
        if isinstance(transport, FleetTransport):
            from ..fleet.balancer import FleetLoader

            return FleetLoader(
                transport.coordinator_addr, src.batch_size,
                src.process_index, src.process_count, put.fn, **common,
            )
        from ..service.client import RemoteLoader

        return RemoteLoader(
            transport.addr, src.batch_size, src.process_index,
            src.process_count, put.fn, **common,
        )

    # -- describe (no compile) ---------------------------------------------

    def cursor_owner(self) -> str:
        """Which node's engine owns the graph-root resume cursor: the
        placement plane counts CONSUMED batches when present; otherwise
        the stream root (transport for remote graphs, source engine for
        in-process ones)."""
        if self.node("place") is not None:
            return type(self.node("place")).__name__
        transport = self.transport
        if isinstance(transport, (ServiceTransport, FleetTransport)):
            return type(transport).__name__
        return type(self.source).__name__

    def describe(self) -> dict:
        owner = self.cursor_owner()
        nodes = []
        for node in self.nodes:
            d = node.describe()
            d["cursor"] = type(node).__name__ == owner
            nodes.append(d)
        return {
            "nodes": nodes,
            "cursor_owner": owner,
            "tunable_nodes": [
                type(n).__name__ for n in self.nodes if n.tunable_names
            ],
        }

    # -- the loader contract (delegated to the compiled engine) ------------

    def __iter__(self):
        return iter(self.compile())

    def __len__(self) -> int:
        return len(self.compile())

    def state_dict(self) -> dict:
        """The ONE resume cursor at the graph root (contract:
        ``data/pipeline.py`` module docstring) — delegated to the engine
        that owns it, so legacy and graph paths serialize identically.
        Reads never compile (compilation may dial sockets or open
        datasets — cursor serialization must stay a pure read): before
        compile the cursor is whatever was staged, origin otherwise."""
        runtime = self._runtime
        if runtime is None:
            return (
                dict(self._pending_state)
                if self._pending_state is not None else {"step": 0}
            )
        return runtime.state_dict()

    def load_state_dict(self, state: dict) -> None:
        """Position the cursor: staged when the graph has not compiled
        yet (compile() applies it), delegated live otherwise."""
        step = int(state.get("step", 0))
        if step < 0:
            raise ValueError(f"negative resume cursor: {step}")
        runtime = self._runtime
        if runtime is None:
            self._pending_state = dict(state)
            return
        runtime.load_state_dict(state)

    def set_prefetch(self, depth: int) -> int:
        return self.compile().set_prefetch(depth)

    def tunables(self):
        """The single autotuner aggregation: the compiled engine already
        chains plane → loader → decoder knobs; the graph root is where
        ``collect_tunables`` picks them all up."""
        return self.compile().tunables()

    def __getattr__(self, name: str):
        # Engine-specific surface (counters, placement_counters,
        # num_classes, set_epoch, stripe_width, ...) falls through to the
        # compiled runtime; dunders and graph internals never delegate.
        if name.startswith("__") or name in (
            "nodes", "_by_kind", "_runtime", "_engine",
        ):
            raise AttributeError(name)
        runtime = self.compile()
        try:
            return getattr(runtime, name)
        except AttributeError:
            # A Place wrap narrows the surface to the loader contract;
            # engine-only attributes live one layer down.
            engine = self._engine
            if engine is not None and engine is not runtime:
                return getattr(engine, name)
            raise

    def __repr__(self) -> str:
        chain = " -> ".join(type(n).__name__ for n in self.nodes)
        return f"LoaderGraph({chain})"


# -- canonical shapes (describe-only, for `ldt graph --loader`) -------------


def canonical_graphs() -> "dict[str, LoaderGraph]":
    """The five loader shapes as spec-only graphs — no dataset, socket, or
    decoder is touched; these exist so ``ldt graph --loader`` can render
    the node topology (and so the README's composition examples have a
    single executable source of truth)."""
    decode_stub = Decode(lambda table: table)  # in-process seam marker
    return {
        "train-iterable": LoaderGraph(
            LanceSource(None, "batch", 32, 0, 1, shuffle=True),
            decode_stub, Cache(), Pool(), Buffers(), Prefetch(2),
            InProcess(), Place(),
        ),
        "train-map-style": LoaderGraph(
            MapStyleSource(None, 32, 0, 1),
            decode_stub, Cache(), Pool(), Buffers(), Prefetch(2),
            InProcess(),
        ),
        "train-folder": LoaderGraph(
            FolderSource(None, 32, 0, 1),
            decode_stub, Cache(), Pool(), Buffers(), Prefetch(2),
            InProcess(),
        ),
        "service": LoaderGraph(
            LanceSource(None, "batch", 32, 0, 1,
                        dataset_fingerprint="<hello-skew-check>"),
            Decode(task_type="classification", image_size=224),
            Buffers(), Prefetch(2),
            ServiceTransport("host:5055"),
        ),
        "fleet": LoaderGraph(
            LanceSource(None, "batch", 32, 0, 1,
                        dataset_fingerprint="<hello-skew-check>"),
            Decode(task_type="classification", image_size=224),
            Buffers(), Prefetch(2),
            FleetTransport("coordinator:5060"),
        ),
    }
