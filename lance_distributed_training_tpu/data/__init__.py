"""Data subsystem: columnar storage, sampler plans, decode, input pipeline."""

from .format import Dataset, Fragment, write_dataset  # noqa: F401
from .samplers import (  # noqa: F401
    ReadRange,
    full_scan_plan,
    sharded_batch_plan,
    sharded_fragment_plan,
    distributed_indices,
    assert_equal_step_counts,
    make_plan,
)
from .decode import (  # noqa: F401
    ImageClassificationDecoder,
    ImageTextDecoder,
    decode_tensor_image,
    numeric_decoder,
)
from .pipeline import (  # noqa: F401
    DataPipeline,
    MapStylePipeline,
    make_eval_pipeline,
    make_train_pipeline,
    make_map_style_pipeline,
)
from .authoring import (  # noqa: F401
    create_dataset_from_image_folder,
    create_food101_datasets,
    create_synthetic_classification_dataset,
    create_synthetic_image_folder,
    create_synthetic_image_text_dataset,
    create_text_token_dataset,
    ingest_on_process_zero,
)
from .cache import (  # noqa: F401
    BatchCache,
    DeviceReplayCache,
    PlanCache,
    decode_fingerprint,
    folder_fingerprint,
    item_fingerprint,
    plan_fingerprint,
)
from .filters import parse_predicate, predicate_mask  # noqa: F401
from .folder import FolderDataPipeline  # noqa: F401
from .graph import (  # noqa: F401
    Buffers,
    Cache,
    Decode,
    DevicePut,
    EvalSource,
    FleetTransport,
    FolderSource,
    InProcess,
    LanceSource,
    LoaderGraph,
    MapStyleSource,
    Place,
    Pool,
    Prefetch,
    ServiceTransport,
)
from .placement import PlacedLoader, PlacementPlane  # noqa: F401
from .workers import WorkerPool, columnar_spec, folder_spec  # noqa: F401
