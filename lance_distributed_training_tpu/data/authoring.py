"""Dataset authoring — the ``create_datasets/`` equivalent (L1 in SURVEY.md §1).

The reference streams torchvision Food101, re-encodes each PIL image to JPEG
bytes, accumulates pyarrow arrays, and writes a Lance dataset with controlled
fragment size (``/root/reference/create_datasets/classification.py:13-63``,
schema ``{image: binary, label: int64}`` at ``:50-53``). This module does the
same against any on-disk image-folder tree (torchvision isn't in this
environment), plus synthetic and text authoring for the other BASELINE
configs.
"""

from __future__ import annotations

import io
import os
from typing import Iterator, Optional, Sequence

import numpy as np
import pyarrow as pa

from .format import Dataset, write_dataset

__all__ = [
    "create_dataset_from_image_folder",
    "create_food101_datasets",
    "create_synthetic_classification_dataset",
    "create_synthetic_image_folder",
    "create_synthetic_image_text_dataset",
    "create_text_token_dataset",
    "create_variable_length_token_dataset",
    "ingest_on_process_zero",
    "IMAGE_SCHEMA",
]

# Schema parity: create_datasets/classification.py:50-53.
IMAGE_SCHEMA = pa.schema([("image", pa.binary()), ("label", pa.int64())])

_IMAGE_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".webp"}


def _folder_samples(root: str) -> tuple[list[tuple[str, int]], list[str]]:
    """ImageFolder convention: root/<class_name>/<image files>."""
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
    )
    samples = []
    for label, cls in enumerate(classes):
        cls_dir = os.path.join(root, cls)
        for name in sorted(os.listdir(cls_dir)):
            if os.path.splitext(name)[1].lower() in _IMAGE_EXTS:
                samples.append((os.path.join(cls_dir, name), label))
    return samples, classes


def create_dataset_from_image_folder(
    root_path: str,
    output_path: str,
    fragment_size: int = 12500,
    batch_size: int = 1024,
    reencode_jpeg_quality: Optional[int] = None,
    shuffle_seed: Optional[int] = None,
) -> Dataset:
    """Image-folder tree → fragmented columnar dataset.

    Mirrors ``create_lance_from_classification_dataset``
    (``create_datasets/classification.py:13-17``): a lazy record-batch
    generator (never holds the full dataset, ``:24-47``), batches of
    ``batch_size`` rows, fragments capped at ``fragment_size`` rows
    (``:55-61``). JPEG files are passed through byte-identical unless
    ``reencode_jpeg_quality`` is set (the reference always re-encodes,
    ``:27-29``; pass-through is strictly faster and lossless).
    """
    samples, classes = _folder_samples(root_path)
    if not samples:
        raise ValueError(f"no images under {root_path}")
    if shuffle_seed is not None:
        rng = np.random.default_rng(shuffle_seed)
        samples = [samples[i] for i in rng.permutation(len(samples))]

    def gen() -> Iterator[pa.RecordBatch]:
        images, labels = [], []
        for path, label in samples:
            with open(path, "rb") as f:
                payload = f.read()
            if reencode_jpeg_quality is not None or not path.lower().endswith(
                (".jpg", ".jpeg")
            ):
                from PIL import Image

                img = Image.open(io.BytesIO(payload)).convert("RGB")
                buf = io.BytesIO()
                img.save(buf, format="JPEG",
                         quality=reencode_jpeg_quality or 85)
                payload = buf.getvalue()
            images.append(payload)
            labels.append(label)
            if len(images) >= batch_size:
                yield pa.record_batch(
                    [pa.array(images, pa.binary()), pa.array(labels, pa.int64())],
                    schema=IMAGE_SCHEMA,
                )
                images, labels = [], []
        if images:
            yield pa.record_batch(
                [pa.array(images, pa.binary()), pa.array(labels, pa.int64())],
                schema=IMAGE_SCHEMA,
            )

    ds = write_dataset(
        gen(), output_path, schema=IMAGE_SCHEMA, mode="overwrite",
        max_rows_per_file=fragment_size,
    )
    # Fragment-count report, as the reference prints (classification.py:63).
    print(f"wrote {ds.count_rows()} rows in {len(ds.get_fragments())} fragments "
          f"({len(classes)} classes)")
    return ds


def ingest_on_process_zero(output_path, ingest_fn) -> Dataset:
    """Run ``ingest_fn`` on process 0 only; other processes wait at a global
    barrier, then every process opens the finished dataset.

    The reference's rank-0 download coordination — the double-barrier around
    ``Food101(download=True)`` (``/root/reference/torch_version/map_style.py:
    49-55``, ``iter_style.py:59-65``) — translated to JAX: one
    ``sync_global_devices`` after ingestion gives the same guarantee (no
    process opens the dataset before process 0 finished writing it; the
    writer's final manifest rename is atomic). No-op fast path when the
    dataset already exists everywhere.

    ``output_path`` may be a sequence of paths when ``ingest_fn`` writes
    several datasets (e.g. :func:`create_food101_datasets`'s train + test):
    ingestion is skipped only when EVERY manifest exists, so a run killed
    between the two writes re-ingests instead of being silently skipped
    forever. Returns the Dataset at the first path.
    """
    from ..parallel.mesh import process_topology, sync_global_devices

    paths = (
        [str(output_path)]
        if isinstance(output_path, (str, os.PathLike))
        else [str(p) for p in output_path]
    )
    process_index, process_count = process_topology()
    exists = all(
        os.path.exists(os.path.join(p, "manifest.json")) for p in paths
    )
    if (process_index == 0 or process_count == 1) and not exists:
        ingest_fn()
    sync_global_devices("ingest_on_process_zero")
    return Dataset(paths[0])


def create_food101_datasets(
    source: str,
    output_root: str,
    fragment_size: int = 12500,
    batch_size: int = 1024,
) -> tuple[Dataset, Dataset]:
    """Real-data recipe: the Food-101 archive → train + test columnar datasets.

    The reference's end-to-end path downloads Food101 via torchvision and
    re-encodes every image (``/root/reference/create_datasets/
    classification.py:19-29``); this environment has no network egress, so
    ``source`` is a local ``food-101.tar.gz`` (the ETHZ archive) or an
    already-extracted ``food-101/`` directory. Images pass through
    byte-identical (they are JPEGs already); the official
    ``meta/train.txt``/``meta/test.txt`` splits drive the two outputs, and
    labels index into sorted ``meta/classes.txt`` — the torchvision Food101
    label convention.

    Multi-host: wrap in :func:`ingest_on_process_zero` so only one process
    ingests::

        ingest_on_process_zero(
            out / "train",
            lambda: create_food101_datasets(tarball, out),
        )
    """
    root = str(source)
    extract_dir = None
    if os.path.isfile(root):
        import tarfile
        import tempfile

        # Extract to a temp dir and remove it after writing — the real
        # archive is ~5 GB of JPEGs; leaving the raw tree next to the
        # columnar output would double the footprint permanently.
        extract_dir = tempfile.mkdtemp(prefix="food101-extract-")
        with tarfile.open(root) as tar:
            tar.extractall(extract_dir, filter="data")
        root = os.path.join(extract_dir, "food-101")
    if not os.path.isdir(os.path.join(root, "meta")):
        raise FileNotFoundError(
            f"{root} is not a food-101 tree (expected meta/ + images/)"
        )

    with open(os.path.join(root, "meta", "classes.txt")) as f:
        classes = sorted(line.strip() for line in f if line.strip())
    class_index = {c: i for i, c in enumerate(classes)}

    def write_split(split: str) -> Dataset:
        with open(os.path.join(root, "meta", f"{split}.txt")) as f:
            entries = [line.strip() for line in f if line.strip()]

        def gen() -> Iterator[pa.RecordBatch]:
            images, labels = [], []
            for entry in entries:  # "apple_pie/1005649"
                cls = entry.split("/", 1)[0]
                with open(os.path.join(root, "images", entry + ".jpg"), "rb") as fh:
                    images.append(fh.read())
                labels.append(class_index[cls])
                if len(images) >= batch_size:
                    yield pa.record_batch(
                        [pa.array(images, pa.binary()),
                         pa.array(labels, pa.int64())],
                        schema=IMAGE_SCHEMA,
                    )
                    images, labels = [], []
            if images:
                yield pa.record_batch(
                    [pa.array(images, pa.binary()), pa.array(labels, pa.int64())],
                    schema=IMAGE_SCHEMA,
                )

        ds = write_dataset(
            gen(), os.path.join(str(output_root), split),
            schema=IMAGE_SCHEMA, mode="overwrite",
            max_rows_per_file=fragment_size,
        )
        print(f"food101 {split}: {ds.count_rows()} rows, "
              f"{len(ds.get_fragments())} fragments")
        return ds

    try:
        return write_split("train"), write_split("test")
    finally:
        if extract_dir is not None:
            import shutil

            shutil.rmtree(extract_dir, ignore_errors=True)


def create_synthetic_image_folder(
    root: str,
    rows: int,
    num_classes: int = 101,
    image_size: int = 224,
    unique_images: int = 64,
    seed: int = 0,
    jpeg_quality: int = 85,
) -> str:
    """Synthetic ImageFolder tree (``root/class_XXX/*.jpg``) — the
    file-based control-arm twin of
    :func:`create_synthetic_classification_dataset`, sharing its 64-image
    JPEG pool recipe so columnar-vs-folder benchmarks read comparable
    bytes (torch_version/ control arm, reference ``README.md:286-290``).

    Each unique pool image is written to disk once and hardlinked into the
    remaining slots: at benchmark scale (10k+ rows) this cuts tree-building
    I/O by the pool-duplication factor with identical read-side behavior —
    which matters when the tree is built inside a scarce accelerator
    window. Falls back to a copy where hardlinks aren't supported.
    """
    from PIL import Image

    rng = np.random.default_rng(seed)
    pool = []
    for _ in range(min(unique_images, max(rows, 1))):
        arr = (rng.random((image_size, image_size, 3)) * 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=jpeg_quality)
        pool.append(buf.getvalue())
    first_path: list = [None] * len(pool)
    per_class = max(rows // num_classes, 1)
    done = 0
    for c in range(num_classes):
        cdir = os.path.join(root, f"class_{c:03d}")
        os.makedirs(cdir, exist_ok=True)
        take = per_class if c < num_classes - 1 else rows - done
        for i in range(take):
            idx = (done + i) % len(pool)
            path = os.path.join(cdir, f"{i:05d}.jpg")
            if first_path[idx] is None:
                with open(path, "wb") as f:
                    f.write(pool[idx])
                first_path[idx] = path
            else:
                try:
                    os.link(first_path[idx], path)
                except OSError:
                    with open(path, "wb") as f:
                        f.write(pool[idx])
        done += take
        if done >= rows:
            break
    return root


def create_synthetic_classification_dataset(
    output_path: str,
    rows: int,
    num_classes: int = 101,
    image_size: int = 224,
    fragment_size: int = 12500,
    unique_images: int = 64,
    seed: int = 0,
    jpeg_quality: int = 85,
) -> Dataset:
    """FOOD101-shaped synthetic dataset for tests and benchmarks."""
    from PIL import Image

    rng = np.random.default_rng(seed)
    pool = []
    for _ in range(min(unique_images, rows)):
        arr = (rng.random((image_size, image_size, 3)) * 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=jpeg_quality)
        pool.append(buf.getvalue())

    def gen() -> Iterator[pa.RecordBatch]:
        done = 0
        while done < rows:
            n = min(4096, rows - done)
            images = [pool[(done + i) % len(pool)] for i in range(n)]
            labels = rng.integers(0, num_classes, n)
            yield pa.record_batch(
                [pa.array(images, pa.binary()), pa.array(labels, pa.int64())],
                schema=IMAGE_SCHEMA,
            )
            done += n

    return write_dataset(
        gen(), output_path, schema=IMAGE_SCHEMA, mode="overwrite",
        max_rows_per_file=fragment_size,
    )


def create_synthetic_image_text_dataset(
    output_path: str,
    rows: int,
    seq_len: int = 16,
    vocab_size: int = 1000,
    image_size: int = 224,
    fragment_size: int = 12500,
    unique_images: int = 64,
    seed: int = 0,
    jpeg_quality: int = 85,
) -> Dataset:
    """LAION-shaped mixed-modal dataset: {image: JPEG binary, input_ids,
    attention_mask} — the CLIP contrastive BASELINE config ("LAION-subset
    image+caption → CLIP (mixed-modal collate)"). Captions are pre-tokenised
    fixed-size-list columns, images JPEG bytes; the decode hook is
    :class:`..decode.ImageTextDecoder`."""
    from PIL import Image

    rng = np.random.default_rng(seed)
    pool = []
    for _ in range(min(unique_images, rows)):
        arr = (rng.random((image_size, image_size, 3)) * 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=jpeg_quality)
        pool.append(buf.getvalue())
    schema = pa.schema(
        [
            ("image", pa.binary()),
            ("input_ids", pa.list_(pa.int32(), seq_len)),
            ("attention_mask", pa.list_(pa.int8(), seq_len)),
        ]
    )

    def gen() -> Iterator[pa.RecordBatch]:
        done = 0
        while done < rows:
            n = min(4096, rows - done)
            images = [pool[(done + i) % len(pool)] for i in range(n)]
            lengths = rng.integers(seq_len // 2, seq_len + 1, n)
            ids = [
                list(rng.integers(2, vocab_size, length))
                + [0] * (seq_len - length)
                for length in lengths
            ]
            mask = [
                [1] * length + [0] * (seq_len - length) for length in lengths
            ]
            yield pa.record_batch(
                [
                    pa.array(images, pa.binary()),
                    pa.array(ids, schema.field("input_ids").type),
                    pa.array(mask, schema.field("attention_mask").type),
                ],
                schema=schema,
            )
            done += n

    return write_dataset(
        gen(), output_path, schema=schema, mode="overwrite",
        max_rows_per_file=fragment_size,
    )


def create_text_token_dataset(
    output_path: str,
    token_ids: Sequence[Sequence[int]],
    seq_len: int,
    fragment_size: int = 50000,
    pad_id: int = 0,
    pack: bool = True,
) -> Dataset:
    """Tokenised text → packed fixed-length rows (the C4/BERT BASELINE config).

    Documents are greedily packed into ``seq_len`` windows (or padded, with
    ``pack=False``) so every row is a fixed-size-list column — static shapes,
    zero-copy to numpy, no per-row host work at train time.
    """
    schema = pa.schema(
        [
            ("input_ids", pa.list_(pa.int32(), seq_len)),
            ("attention_mask", pa.list_(pa.int8(), seq_len)),
        ]
    )

    def rows() -> Iterator[tuple[list[int], list[int]]]:
        if pack:
            buf: list[int] = []
            for doc in token_ids:
                buf.extend(doc)
                while len(buf) >= seq_len:
                    yield buf[:seq_len], [1] * seq_len
                    buf = buf[seq_len:]
            if buf:
                mask = [1] * len(buf) + [0] * (seq_len - len(buf))
                yield buf + [pad_id] * (seq_len - len(buf)), mask
        else:
            for doc in token_ids:
                doc = list(doc)[:seq_len]
                mask = [1] * len(doc) + [0] * (seq_len - len(doc))
                yield doc + [pad_id] * (seq_len - len(doc)), mask

    def gen() -> Iterator[pa.RecordBatch]:
        ids_buf, mask_buf = [], []
        for ids, mask in rows():
            ids_buf.append(ids)
            mask_buf.append(mask)
            if len(ids_buf) >= 4096:
                yield pa.record_batch(
                    [
                        pa.array(ids_buf, schema.field("input_ids").type),
                        pa.array(mask_buf, schema.field("attention_mask").type),
                    ],
                    schema=schema,
                )
                ids_buf, mask_buf = [], []
        if ids_buf:
            yield pa.record_batch(
                [
                    pa.array(ids_buf, schema.field("input_ids").type),
                    pa.array(mask_buf, schema.field("attention_mask").type),
                ],
                schema=schema,
            )

    return write_dataset(
        gen(), output_path, schema=schema, mode="overwrite",
        max_rows_per_file=fragment_size,
    )


def create_variable_length_token_dataset(
    output_path: str,
    rows: int,
    vocab_size: int = 1000,
    max_len: int = 128,
    mean_len: float = 24.0,
    sigma: float = 0.7,
    fragment_size: int = 50000,
    seed: int = 0,
    include_mask: bool = False,
) -> Dataset:
    """Variable-length token corpus — the ragged token plane's test/bench
    dataset (no real tokenizer needed).

    Schema: ``{input_ids: list_<int32>}`` (plus an all-ones variable
    ``attention_mask`` list column with ``include_mask=True`` — packed
    decoding regenerates the mask on device, so the default schema skips
    it). Row lengths draw from a seeded **clipped lognormal** — the
    long-tail shape real tokenized text shows (most sequences far below
    the max, a heavy tail touching it), which is exactly the distribution
    where dataset-max padding burns the most FLOPs: with the defaults
    (mean ~24, max 128) a fixed-shape loader pads ~80% dead tokens.
    Everything is a pure function of ``seed`` — two hosts authoring the
    same arguments produce byte-identical datasets (the
    :func:`~.format.Dataset.fingerprint` skew check depends on it).
    """
    rng = np.random.default_rng(seed)
    schema_fields = [("input_ids", pa.list_(pa.int32()))]
    if include_mask:
        schema_fields.append(("attention_mask", pa.list_(pa.int8())))
    schema = pa.schema(schema_fields)

    def gen() -> Iterator[pa.RecordBatch]:
        done = 0
        while done < rows:
            n = min(4096, rows - done)
            lengths = np.clip(
                rng.lognormal(np.log(mean_len), sigma, n).astype(np.int64),
                1, max_len,
            )
            ids = [
                rng.integers(2, vocab_size, int(L), dtype=np.int32)
                for L in lengths
            ]
            arrays = [pa.array(ids, schema.field("input_ids").type)]
            if include_mask:
                arrays.append(pa.array(
                    [np.ones(int(L), np.int8) for L in lengths],
                    schema.field("attention_mask").type,
                ))
            yield pa.record_batch(arrays, schema=schema)
            done += n

    return write_dataset(
        gen(), output_path, schema=schema, mode="overwrite",
        max_rows_per_file=fragment_size,
    )


def main(argv=None) -> None:
    """Dataset-authoring CLI — the ``create_datasets/classification.py``
    script equivalent (``/root/reference/create_datasets/classification.py:
    69-70``, flags ``:13-17``)::

        python -m lance_distributed_training_tpu.data.authoring folder \
            --root_path /data/food101_files --output_path /data/food101.ldt \
            --fragment_size 12500
    """
    import argparse

    p = argparse.ArgumentParser(description="Author a columnar dataset")
    sub = p.add_subparsers(dest="kind", required=True)

    folder = sub.add_parser("folder", help="image-folder tree → dataset")
    folder.add_argument("--root_path", required=True)
    folder.add_argument("--output_path", required=True)
    folder.add_argument("--fragment_size", type=int, default=12500)
    folder.add_argument("--batch_size", type=int, default=1024)
    folder.add_argument("--reencode_jpeg_quality", type=int, default=None)
    folder.add_argument("--shuffle_seed", type=int, default=None)

    synth = sub.add_parser("synthetic", help="synthetic classification dataset")
    synth.add_argument("--output_path", required=True)
    synth.add_argument("--rows", type=int, required=True)
    synth.add_argument("--num_classes", type=int, default=101)
    synth.add_argument("--image_size", type=int, default=224)
    synth.add_argument("--fragment_size", type=int, default=12500)

    tokens = sub.add_parser(
        "tokens", help="variable-length synthetic token dataset (long-tail "
                       "lengths; the ragged token plane's corpus)"
    )
    tokens.add_argument("--output_path", required=True)
    tokens.add_argument("--rows", type=int, required=True)
    tokens.add_argument("--vocab_size", type=int, default=1000)
    tokens.add_argument("--max_len", type=int, default=128)
    tokens.add_argument("--mean_len", type=float, default=24.0)
    tokens.add_argument("--seed", type=int, default=0)
    tokens.add_argument("--fragment_size", type=int, default=50000)

    food = sub.add_parser(
        "food101", help="food-101 archive/tree → train + test datasets"
    )
    food.add_argument("--source", required=True,
                      help="food-101.tar.gz or extracted food-101/ dir")
    food.add_argument("--output_root", required=True)
    food.add_argument("--fragment_size", type=int, default=12500)

    args = p.parse_args(argv)
    if args.kind == "synthetic":
        create_synthetic_classification_dataset(
            args.output_path, args.rows, num_classes=args.num_classes,
            image_size=args.image_size, fragment_size=args.fragment_size,
        )
    elif args.kind == "tokens":
        create_variable_length_token_dataset(
            args.output_path, args.rows, vocab_size=args.vocab_size,
            max_len=args.max_len, mean_len=args.mean_len, seed=args.seed,
            fragment_size=args.fragment_size,
        )
    elif args.kind == "food101":
        create_food101_datasets(
            args.source, args.output_root, fragment_size=args.fragment_size
        )
    else:  # "folder" — the only other registered subcommand
        create_dataset_from_image_folder(
            args.root_path, args.output_path,
            fragment_size=args.fragment_size, batch_size=args.batch_size,
            reencode_jpeg_quality=args.reencode_jpeg_quality,
            shuffle_seed=args.shuffle_seed,
        )


if __name__ == "__main__":
    main()
