"""Pooled-buffer memory plane: recycled decode pages + shared-memory IPC.

The r5 A/B (`PERF_NOTES_r05.md` §1) showed every loader arm bottoming out at
the host's decode+copy rate. Two of the copies are pure overhead:

* **output-buffer faulting** — each decoded batch faulted a fresh
  ``np.empty`` (~38 MB at 512×224px), so the kernel zero-fills new pages on
  every batch while warm, already-faulted pages from two batches ago sit in
  the allocator. :class:`BufferPool` keeps those pages alive and hands them
  back out: lease-based, keyed by ``(shape, dtype)``, thread-safe, bounded.
* **IPC pickling** — every worker-pool batch was pickled across the process
  boundary (serialise + pipe write + pipe read + deserialise = four full
  copies of the batch). :class:`ShmRing`/:class:`ShmSlotWriter` replace that
  with ``multiprocessing.shared_memory`` ring slots: the worker writes the
  decoded tensors into a slot and returns only a tiny descriptor ``(slot,
  shapes, dtypes, offsets)``; the consumer maps the same physical pages and
  copies once into a pooled buffer.

Lease-safety model (why release() can run before the data is dead): a
released page is only *recycled* once nothing else references it.
``jax.device_put`` on the CPU backend may zero-copy **alias** the numpy
buffer (jaxlib's ``kImmutableZeroCopy`` host-buffer semantics), and on
accelerator backends the runtime holds the source buffer until the async
H2D transfer completes — in both cases the jax machinery holds a Python
reference to the array. :meth:`BufferPool.release` therefore parks the page
on a *pending* list and a sweep recycles it only when ``sys.getrefcount``
shows the pool as the sole owner. Callers can release eagerly (right after
``device_put`` dispatch, or right after ``yield``) without ever corrupting
an in-flight transfer or an aliased device array.

Shared-memory lifecycle (Python 3.10 resource-tracker semantics): every
process that creates *or attaches* a segment registers its name with the
shared ``resource_tracker`` (a set, so re-registration is a no-op). We never
unregister manually — each segment is unlinked exactly once via
``SharedMemory.unlink()`` (which unregisters), in :meth:`ShmRing.cleanup`,
driven by ``WorkerPool.shutdown()`` or its ``weakref.finalize`` guard. Slot
names are deterministic (``ldtshm_<session>_<slot>``), so cleanup unlinks
every slot even when the worker that created it already crashed; the
tracker remains as the last-resort reaper if the whole process dies without
running finalizers.

Thread & queue policy: the free-slot queue is bounded (``nslots`` + poison
headroom) and every blocking ``get`` carries a timeout with a pickle
fallback, so a lost slot token (worker killed mid-batch) degrades
throughput instead of deadlocking the pool.

Metrics (process registry, served by ``/metrics``): ``bufpool_hit_total`` /
``bufpool_miss_total`` / ``bufpool_evict_total`` / ``bufpool_in_use`` /
``bufpool_pending`` and ``shm_batches_total`` / ``shm_bytes_total`` /
``shm_slot_resizes_total`` / ``shm_fallback_total`` / ``shm_slot_wait_ms``.
"""

from __future__ import annotations

import sys
import threading
import time
import uuid
import weakref
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..obs.registry import MetricsRegistry, default_registry
from ..utils import leaktrack

__all__ = [
    "BufferPool",
    "RaggedPage",
    "default_buffer_pool",
    "ShmRing",
    "ShmSlotWriter",
    "shm_available",
]


class RaggedPage(NamedTuple):
    """One variable-length column's pooled pages: a flat ``values`` page
    sized to a capacity *bucket* (so batches of nearby token counts recycle
    the same physical pages) and an exact ``offsets`` page. Both are
    ordinary pool leases — ``release``/``release_batch`` on the arrays (the
    consumer's existing discipline) reclaims them; there is no separate
    ragged release verb to forget."""

    values: np.ndarray  # [capacity_bucket] — caller fills [:total]
    offsets: np.ndarray  # int32 [n_sequences + 1]
    capacity: int  # the bucket the values page was keyed under

# 64-byte alignment for tensor offsets inside a shm slot (cache-line; also
# satisfies every numpy dtype's alignment requirement).
_ALIGN = 64


def _solo_refcount() -> int:
    """Calibrate the refcount a pending-list entry shows when the pool is
    its sole owner: one ref from the list, one from the loop variable, one
    from ``getrefcount``'s own argument binding. Computed (not hardcoded)
    so an interpreter that counts differently cannot make the sweep recycle
    a page something still reads."""
    lst = [object()]  # no extra name binding: mirror the sweep loop exactly
    for x in lst:
        return sys.getrefcount(x)
    raise AssertionError("unreachable")


_SOLO_REFS = _solo_refcount()


class BufferPool:
    """Lease-based pool of recycled numpy output buffers.

    ``lease(shape, dtype)`` returns a warm page when one is free (hit) or
    faults a fresh ``np.empty`` (miss). ``release(arr)`` gives the page
    back; it is recycled only once the pool is its sole referent (see the
    module docstring's lease-safety model), so eager release after
    ``device_put`` dispatch is always safe. Arrays the pool never leased
    are ignored by ``release`` — callers can blanket-release a whole batch
    dict without tracking which values were pooled.
    """

    def __init__(
        self,
        max_free_per_key: int = 8,
        max_pending: int = 32,
        registry: Optional[MetricsRegistry] = None,
    ):
        self._lock = threading.Lock()
        self._free: Dict[Tuple, List[np.ndarray]] = {}
        # id(arr) -> weakref. WEAK on purpose: a leased page someone drops
        # without releasing (early generator close, a crashed consumer, a
        # forgotten teardown drain) must degrade to ordinary garbage — a
        # missed recycle — never a permanent leak pinned by the pool. The
        # callback (no pool lock: runs at GC time) retires the entry.
        self._outstanding: Dict[int, weakref.ref] = {}
        self._pending: List[np.ndarray] = []  # released, still referenced
        self.max_free_per_key = max(0, max_free_per_key)
        self.max_pending = max(1, max_pending)
        reg = registry if registry is not None else default_registry()
        self._hits = reg.counter("bufpool_hit_total")
        self._misses = reg.counter("bufpool_miss_total")
        self._evicts = reg.counter("bufpool_evict_total")
        self._in_use = reg.gauge("bufpool_in_use")
        self._pending_gauge = reg.gauge("bufpool_pending")
        self._ragged_leases = reg.counter("bufpool_ragged_leases_total")
        self._ragged_slack = reg.counter("bufpool_ragged_slack_bytes_total")

    @staticmethod
    def _key(shape, dtype) -> Tuple:
        return (tuple(int(s) for s in shape), np.dtype(dtype).str)

    def _stash_locked(self, arr: np.ndarray) -> None:
        key = self._key(arr.shape, arr.dtype)
        free = self._free.setdefault(key, [])
        if len(free) < self.max_free_per_key:
            free.append(arr)
        else:
            self._evicts.inc()  # cap reached: let the page be garbage

    def _sweep_locked(self) -> None:
        still: List[np.ndarray] = []
        for arr in self._pending:
            # One ref each: self._pending, the loop variable, getrefcount's
            # argument — _SOLO_REFS exactly. More means a consumer, a live
            # batch dict, or jax (alias / in-flight transfer) still holds
            # the page: not recyclable yet.
            if sys.getrefcount(arr) <= _SOLO_REFS:
                self._stash_locked(arr)
            else:
                still.append(arr)
        if len(still) > self.max_pending:
            # Bound the deferred set: the overflow pages are dropped from
            # the pool entirely (their external holders keep them alive;
            # they just never recycle).
            self._evicts.inc(len(still) - self.max_pending)
            still = still[-self.max_pending:]
        self._pending = still
        self._pending_gauge.set(len(still))

    def lease(self, shape: Sequence[int], dtype) -> np.ndarray:
        key = self._key(shape, dtype)
        arr: Optional[np.ndarray] = None
        with self._lock:
            self._sweep_locked()
            free = self._free.get(key)
            if free:
                arr = free.pop()
                self._hits.inc()
            else:
                self._misses.inc()
        if arr is None:
            arr = np.empty(tuple(shape), np.dtype(dtype))
        outstanding = self._outstanding
        gauge = self._in_use

        def _dropped(_ref, _key=id(arr)):
            # Lease died unreleased: retire the entry (plain dict pop, no
            # pool lock — this runs from the GC) so the id can be reused.
            outstanding.pop(_key, None)
            gauge.set(len(outstanding))
            if leaktrack.enabled():
                # The leak event itself, caught live: a page dropped
                # without release (LDT1201's witness corroboration).
                leaktrack.track_dropped("pool-page", _key)

        with self._lock:
            outstanding[id(arr)] = weakref.ref(arr, _dropped)
            gauge.set(len(outstanding))
        if leaktrack.enabled():
            # depth 3: past this frame and the hook, to lease()'s caller —
            # the static ownership model's acquire-site join key.
            leaktrack.track_acquire("pool-page", id(arr), depth=3)
        return arr

    def lease_ragged(self, total: int, n_sequences: int,
                     values_dtype) -> RaggedPage:
        """Lease one variable-length column's page pair (see
        :class:`RaggedPage`). The values page is keyed by its **capacity
        bucket** (next power of two ≥ ``total``), not the exact token
        count — without the bucket, every distinct batch token total would
        mint its own free-list key and the pool would never recycle a
        ragged page (the fragmentation the r15 tentpole removes). Both
        pages ride the ordinary lease/release discipline — the LDT1201
        ownership analyzer and the ``LDT_LEAK_SANITIZER`` witness track
        them through the same ``BufferPool.lease`` acquire site."""
        from .token_pack import ragged_capacity

        cap = ragged_capacity(int(total))
        values = self.lease((cap,), values_dtype)
        try:
            offsets = self.lease((int(n_sequences) + 1,), np.int32)
        except BaseException:
            # The pair acquires atomically or not at all: a failed offsets
            # lease must not strand the values page (LDT1201's
            # exception-edge class).
            self.release(values)
            raise
        try:
            # Counted only once BOTH pages are held — a MemoryError'd lease
            # must not inflate the ragged series exactly in the degraded
            # runs where an operator reads them.
            self._ragged_leases.inc()
            self._ragged_slack.inc(
                (cap - int(total)) * np.dtype(values_dtype).itemsize
            )
        except BaseException:
            self.release(values)
            self.release(offsets)
            raise
        return RaggedPage(values, offsets, cap)

    def release(self, arr) -> bool:
        """Return a leased page. ``False`` (and a no-op) for arrays this
        pool does not own — safe to call on every value of a mixed batch.
        A *view* of a leased page (a ragged values page sliced to its real
        token count) releases its base: the refcount sweep still defers
        recycling until every view dies, so this is always safe."""
        if not isinstance(arr, np.ndarray):
            return False
        with self._lock:
            ref = self._outstanding.pop(id(arr), None)
            if ref is None or ref() is not arr:  # foreign (or id reuse race)
                # Walk the view chain: releasing batch["c__values"][:n]
                # must find the pooled base page it windows.
                base = arr.base
                hops = 0
                while isinstance(base, np.ndarray) and hops < 4:
                    ref = self._outstanding.pop(id(base), None)
                    if ref is not None and ref() is base:
                        arr = base
                        break
                    base = base.base
                    hops += 1
                else:
                    return False
                if ref is None or ref() is not arr:
                    return False
            self._in_use.set(len(self._outstanding))
            self._pending.append(arr)
            self._sweep_locked()
        if leaktrack.enabled():
            leaktrack.track_release("pool-page", id(arr))
        return True

    def release_batch(self, batch) -> int:
        """Release every pooled value of a ``{name: array}`` batch dict.
        Returns how many were pool-owned."""
        if not isinstance(batch, dict):
            return 0
        return sum(self.release(v) for v in list(batch.values()))

    def set_budget(self, max_free_per_key: int) -> int:
        """Autotune actuator (tune/): resize the recycled-page budget, live.
        Growing lets more warm pages survive between batches (the hit-rate
        lever); shrinking trims every free list to the new cap immediately
        (counted as evictions) — outstanding leases are untouched, so no
        in-flight batch ever loses its page."""
        cap = max(0, int(max_free_per_key))
        with self._lock:
            self.max_free_per_key = cap
            for key, free in self._free.items():
                if len(free) > cap:
                    self._evicts.inc(len(free) - cap)
                    del free[cap:]
        return cap

    def tunables(self):
        """Autotune registration surface: the per-(shape, dtype) free-page
        budget."""
        from ..tune.tunable import Tunable

        return [Tunable(
            "bufpool_pages",
            lambda: self.max_free_per_key,
            self.set_budget,
            lo=2, hi=64,
            doc="recycled pages kept warm per (shape, dtype) key",
        )]

    def sweep(self) -> None:
        """Run one pending→free sweep now. The sweep normally rides every
        ``lease``/``release``; the placement plane's release-at-dispatch
        discipline means the LAST batches of an epoch can sit on the
        pending list until jax drops its transfer references — a steady
        state the next lease clears, but teardown paths and leak asserts
        (tests, the CI smoke) call this to observe 'everything recycled'
        without having to lease again."""
        with self._lock:
            self._sweep_locked()

    def stats(self) -> dict:
        with self._lock:
            return {
                "outstanding": len(self._outstanding),
                "pending": len(self._pending),
                "free": sum(len(v) for v in self._free.values()),
            }


_DEFAULT_POOL: Optional[BufferPool] = None
_DEFAULT_POOL_LOCK = threading.Lock()


def default_buffer_pool() -> BufferPool:
    """The process-wide pool every layer shares (decoder output pages,
    wire-receive pages, shm copy-out pages) — one pool so a page freed by
    one stage warms the next."""
    global _DEFAULT_POOL
    with _DEFAULT_POOL_LOCK:
        if _DEFAULT_POOL is None:
            _DEFAULT_POOL = BufferPool()
        return _DEFAULT_POOL


# -- shared-memory ring -----------------------------------------------------


def shm_available() -> bool:
    """Can this platform back a shm ring? (POSIX shared memory present and
    writable — containers occasionally mount /dev/shm noexec/ro.)"""
    try:
        from multiprocessing import shared_memory

        seg = shared_memory.SharedMemory(create=True, size=16)
        try:
            seg.unlink()
        finally:
            seg.close()
        return True
    except (ImportError, OSError):
        return False


def _slot_name(session: str, slot: int) -> str:
    return f"ldtshm_{session}_{slot}"


def _round_slot_size(nbytes: int) -> int:
    """Slot capacity for a batch of ``nbytes``: 25% headroom rounded up to
    4 KiB pages, so steady-state jitter in batch size (ragged label widths,
    contrastive text columns) doesn't resize every other batch."""
    padded = nbytes + nbytes // 4
    return max(4096, (padded + 4095) // 4096 * 4096)


def _plan_layout(batch: dict) -> Optional[Tuple[list, int]]:
    """``(tensor_metas, total_bytes)`` for writing ``batch`` into one slot;
    ``None`` when the batch isn't a pure dict of numpy arrays (the caller
    then falls back to the pickle transport)."""
    metas = []
    offset = 0
    for name, arr in batch.items():
        if not isinstance(arr, np.ndarray):
            return None
        offset = (offset + _ALIGN - 1) // _ALIGN * _ALIGN
        metas.append([name, arr.dtype.str, list(arr.shape), offset])
        offset += arr.nbytes
    return metas, offset


class ShmSlotWriter:
    """Worker-process half of the ring: acquire a free slot token, size the
    slot's segment to the batch, copy the tensors in, and return a small
    picklable descriptor. Falls back (returns ``None``) when no slot frees
    up within the acquire timeout — liveness is never hostage to a lost
    token."""

    def __init__(self, session: str, free_q, acquire_timeout_s: float = 10.0):
        self.session = session
        self._free_q = free_q
        self.acquire_timeout_s = acquire_timeout_s
        # slot -> (SharedMemory, size) as last seen by THIS process.
        self._segments: Dict[int, Tuple[object, int]] = {}

    def _acquire(self):
        import queue as _queue

        deadline = time.monotonic() + self.acquire_timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                tok = self._free_q.get(timeout=min(0.25, remaining))
            except _queue.Empty:
                continue
            return tok  # (slot, gen, size) or None = shutdown poison

    def _ensure(self, slot: int, gen: int, size: int, needed: int):
        """Attach (or create/resize) the slot's segment with capacity for
        ``needed`` bytes. Returns ``(seg, gen, size)``."""
        from multiprocessing import shared_memory

        name = _slot_name(self.session, slot)
        cached = self._segments.get(slot)
        if needed > size:
            # Resize = unlink + recreate under the same name. Only the
            # token holder touches a slot, so no other process can be
            # mid-write; readers detect staleness by the size change
            # (sizes strictly grow).
            if size > 0:
                if cached is not None and cached[1] == size:
                    old = cached[0]
                else:
                    if cached is not None:
                        cached[0].close()
                    old = shared_memory.SharedMemory(name=name)
                try:
                    old.unlink()
                except FileNotFoundError:
                    pass  # earlier failed resize already removed it
                finally:
                    old.close()
                self._segments.pop(slot, None)
            size = _round_slot_size(needed)
            gen += 1
            seg = self._create(name, size)
            self._segments[slot] = (seg, size)
            return seg, gen, size
        if cached is not None and cached[1] == size:
            return cached[0], gen, size
        if cached is not None:
            cached[0].close()
        if size == 0:
            # A (slot, gen, 0) token after a failed write: the segment may
            # or may not exist — _create below reconciles either way.
            size = _round_slot_size(needed)
            gen += 1
            seg = self._create(name, size)
            self._segments[slot] = (seg, size)
            return seg, gen, size
        seg = shared_memory.SharedMemory(name=name)
        self._segments[slot] = (seg, size)
        return seg, gen, size

    @staticmethod
    def _create(name: str, size: int):
        """Create a segment, reconciling a leftover from a failed earlier
        write (same name, unknown size): unlink it and retry once."""
        from multiprocessing import shared_memory

        try:
            return shared_memory.SharedMemory(name=name, create=True,
                                              size=size)
        except FileExistsError:
            stale = shared_memory.SharedMemory(name=name)
            try:
                stale.unlink()
            finally:
                stale.close()
            return shared_memory.SharedMemory(name=name, create=True,
                                              size=size)

    def write_batch(self, batch: dict) -> Optional[dict]:
        plan = _plan_layout(batch)
        if plan is None:
            return None
        metas, total = plan
        t0 = time.monotonic_ns()
        tok = self._acquire()
        if tok is None:  # timeout or shutdown poison: pickle fallback
            return None
        # Unpack the token FIRST (pure tuple destructuring, cannot raise):
        # from here down the requeue in the except arm owns the slot, so
        # no statement between acquire and the try can strand it (LDT1201).
        slot, gen, size = tok
        wait_ms = (time.monotonic_ns() - t0) / 1e6
        try:
            seg, gen, size = self._ensure(slot, gen, size, total)
            resized = size != tok[2]
            for name, dtype_str, shape, offset in metas:
                dst = np.ndarray(
                    tuple(shape), np.dtype(dtype_str),
                    buffer=seg.buf, offset=offset,
                )
                np.copyto(dst, batch[name])
        except BaseException as exc:
            # Requeue a RESET token (size 0), not the one we were handed:
            # _ensure may have already unlinked the slot's old segment, so
            # the stale (slot, gen, size) would poison every later writer
            # with FileNotFoundError. Size 0 makes the next holder create
            # fresh (reconciling any leftover segment).
            self._segments.pop(slot, None)
            self._free_q.put((slot, gen + 1, 0))
            if isinstance(exc, OSError):
                # E.g. ENOSPC on an undersized /dev/shm (64 MB docker
                # default vs ~48 MB slots): degrade to the pickle
                # transport for this batch instead of killing the epoch —
                # the documented fallback policy.
                return None
            raise
        return {
            "slot": slot, "gen": gen, "size": size, "total": total,
            "wait_ms": round(wait_ms, 3), "resized": resized,
            "tensors": metas,
        }

    def close(self) -> None:
        for seg, _ in self._segments.values():
            try:
                seg.close()
            except (OSError, BufferError):  # BufferError: copy in flight
                pass
        self._segments.clear()


class ShmRing:
    """Parent/consumer half of the ring: owns the slot-token queue and the
    segments' lifecycle. ``read_batch`` maps a descriptor's slot, copies
    the tensors out (into ``BufferPool`` pages when given), and returns the
    token to the free queue — the consumer ack that lets a worker reuse the
    slot."""

    def __init__(
        self,
        nslots: int,
        ctx,
        acquire_timeout_s: float = 10.0,
        registry: Optional[MetricsRegistry] = None,
    ):
        if nslots < 1:
            raise ValueError("ShmRing needs nslots >= 1")
        self.session = uuid.uuid4().hex[:12]
        self.nslots = nslots
        self.acquire_timeout_s = acquire_timeout_s
        # Bounded: at most nslots tokens circulate; the headroom absorbs
        # shutdown poison pills without ever blocking.
        self._free_q = ctx.Queue(maxsize=nslots + 64)
        for slot in range(nslots):
            self._free_q.put((slot, 0, 0))  # size 0 = not yet created
        self._segments: Dict[int, Tuple[object, int]] = {}
        self._closed = False
        self._lock = threading.Lock()
        reg = registry if registry is not None else default_registry()
        self._batches = reg.counter("shm_batches_total")
        self._bytes = reg.counter("shm_bytes_total")
        self._resizes = reg.counter("shm_slot_resizes_total")
        self._fallbacks = reg.counter("shm_fallback_total")
        self._wait_hist = reg.histogram("shm_slot_wait_ms")

    def writer_args(self) -> tuple:
        """The picklable bits a worker needs to build its
        :class:`ShmSlotWriter` (rides ``ProcessPoolExecutor`` initargs —
        legal because initargs travel as spawn-time ``Process`` arguments,
        the one context where an ``mp.Queue`` may be pickled)."""
        return (self.session, self._free_q, self.acquire_timeout_s)

    def _attach(self, slot: int, size: int):
        from multiprocessing import shared_memory

        cached = self._segments.get(slot)
        if cached is not None and cached[1] == size:
            return cached[0]
        if cached is not None:
            cached[0].close()
            self._segments.pop(slot, None)
        seg = shared_memory.SharedMemory(name=_slot_name(self.session, slot))
        self._segments[slot] = (seg, size)
        return seg

    def read_batch(
        self, desc: dict, buffer_pool: Optional[BufferPool] = None
    ) -> dict:
        """Descriptor → ``{name: np.ndarray}`` (freshly owned arrays; the
        slot is released back to the ring before returning)."""
        if self._closed:
            raise RuntimeError("ShmRing is closed")
        slot, gen, size = desc["slot"], desc["gen"], desc["size"]
        out: Dict[str, np.ndarray] = {}
        try:
            # Lock only the attach-cache lookup: the slot's CONTENT is
            # exclusively ours while we hold its token, and serialising
            # the multi-MB copies would bottleneck multi-client servers
            # on one reader thread's memcpy. The attach lives INSIDE the
            # requeue-protected try: a vanished segment (worker died
            # mid-epoch, FileNotFoundError here) must return the token
            # too, not just copy failures.
            with self._lock:
                seg = self._attach(slot, size)
            for name, dtype_str, shape, offset in desc["tensors"]:
                shape = tuple(shape)
                src = np.ndarray(
                    shape, np.dtype(dtype_str), buffer=seg.buf, offset=offset
                )
                if buffer_pool is not None:
                    dst = buffer_pool.lease(shape, dtype_str)
                else:
                    dst = np.empty(shape, np.dtype(dtype_str))
                # Park ownership in `out` BEFORE the copy: if copyto raises
                # (a torn/stale descriptor), the except arm below can
                # release every page it leased so far, dst included.
                out[name] = dst
                np.copyto(dst, src)
        except BaseException:
            # A failed copy-out must not strand resources: return the
            # leased pages to the pool and — critically — the slot token
            # to the ring (a lost token shrinks the ring FOREVER; the
            # writer side already requeues a reset token on its own
            # failures, this is the reader-side mirror).
            if buffer_pool is not None:
                for arr in out.values():
                    buffer_pool.release(arr)
            self._free_q.put((slot, gen, size))
            if leaktrack.enabled():
                leaktrack.track_release("shm-token",
                                        (self.session, slot, gen))
            raise
        self._free_q.put((slot, gen, size))
        if leaktrack.enabled():
            leaktrack.track_release("shm-token", (self.session, slot, gen))
        self._batches.inc()
        self._bytes.inc(desc["total"])
        if desc.get("resized"):
            self._resizes.inc()
        self._wait_hist.observe(desc.get("wait_ms", 0.0))
        return out

    def release_token(self, desc: dict) -> None:
        """Return a descriptor's slot without reading it (teardown path for
        completed-but-unconsumed futures)."""
        if self._closed:
            return
        self._free_q.put((desc["slot"], desc["gen"], desc["size"]))
        if leaktrack.enabled():
            leaktrack.track_release(
                "shm-token", (self.session, desc["slot"], desc["gen"])
            )

    def count_fallback(self) -> None:
        self._fallbacks.inc()

    def poison(self, n: int) -> None:
        """Wake ``n`` workers potentially blocked on slot acquisition so
        executor shutdown can join them."""
        import queue as _queue

        for _ in range(n):
            try:
                self._free_q.put_nowait(None)
            except _queue.Full:
                break

    def cleanup(self) -> None:
        """Unlink every slot segment (whichever process created it — names
        are deterministic) and close the token queue. Idempotent; ignores
        already-gone segments, so it is safe after worker crashes."""
        from multiprocessing import shared_memory

        with self._lock:
            if self._closed:
                return
            self._closed = True
            for seg, _ in self._segments.values():
                try:
                    seg.close()
                except (OSError, BufferError):  # BufferError: copy in flight
                    pass
            self._segments.clear()
            for slot in range(self.nslots):
                try:
                    seg = shared_memory.SharedMemory(
                        name=_slot_name(self.session, slot)
                    )
                except FileNotFoundError:
                    continue
                except OSError:
                    continue
                try:
                    seg.unlink()  # unregisters: balances the create-time register
                finally:
                    seg.close()
            try:
                self._free_q.close()
                self._free_q.cancel_join_thread()
            except (OSError, AttributeError):
                pass
