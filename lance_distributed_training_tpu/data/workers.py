"""Process-pool decode workers — the ``get_safe_loader`` equivalent.

The reference's map-style path gets decode parallelism from torch DataLoader
worker *processes* running ``collate_fn``
(``/root/reference/lance_map_style.py:60-69``, ``num_workers=8``, spawn
context + ``persistent_workers`` at ``torch_version/map_style.py:63-74``),
via upstream's ``get_safe_loader`` — "Safe" because each worker must re-open
the native dataset handle rather than inherit it across ``fork``
(``README.md:24,60``; SURVEY.md §7 "fork-safe w.r.t. the native reader
handle").

Here the same capability is a :class:`WorkerPool`: N spawned processes, each
re-opening the columnar store by URI in its initializer (our ``Dataset``
handles are just memory-maps — cheap to re-open, nothing to inherit), running
read+decode for whole plan items and streaming results back **in plan order**
with a bounded in-flight window. The training process never touches a JPEG.

When to use which decode parallelism:

* ``num_workers=0`` (default): producer thread + native C++ decoder
  (:mod:`..native`) — the decode pool releases the GIL, so threads already
  scale across cores with zero IPC cost. Best when the native path is built.
  (Since r7 neither choice affects H2D: placement runs on the plane's own
  thread downstream of the pool, :mod:`.placement`.)
* ``num_workers>0``: process workers — true parallelism for *Python-bound*
  decode hooks (custom ``to_tensor_fn``/``collate_fn`` plugins that hold the
  GIL). With the default ``transport="shm"`` the decoded tensors cross the
  IPC boundary through ``multiprocessing.shared_memory`` ring slots
  (:mod:`.buffers`): the worker returns only a tiny ``(slot, shapes,
  dtypes, offsets)`` descriptor and the consumer copies once out of the
  mapped pages — replacing the old per-batch pickle (serialise + pipe
  write + pipe read + deserialise ≈ four full copies of ~38 MB of decoded
  uint8 per 512×224px batch). ``transport="pickle"`` keeps the old path
  (the A/B control arm; also the automatic fallback when POSIX shared
  memory is unavailable).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
import time
import weakref
from collections import deque
from concurrent.futures import ProcessPoolExecutor
from typing import Callable, Iterable, Iterator, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from ..obs.registry import default_registry
from ..utils import leaktrack

__all__ = ["WorkerPool", "columnar_spec", "folder_spec", "RETRYABLE_READ_ERRORS"]

# The transient-read failure set shared by every retry surface (in-worker
# retries here, the data-service server's read_item): one definition so the
# policies cannot drift.
RETRYABLE_READ_ERRORS = (OSError, pa.ArrowInvalid)

# Per-worker state, set by the pool initializer (module-global because
# ProcessPoolExecutor task functions must be importable module-level names).
_STATE: Optional[tuple] = None


def columnar_spec(uri: str) -> Tuple[str, object]:
    """Reader spec for a columnar dataset: workers re-open by URI."""
    return ("columnar", str(uri))


def folder_spec(samples: Sequence[Tuple[str, int]]) -> Tuple[str, object]:
    """Reader spec for the folder control arm: (path, label) samples."""
    return ("folder", list(samples))


def _init_worker(reader_spec, decode_fn, columns=None,
                 read_retries=1, retry_backoff_s=0.05,
                 shm_args=None) -> None:
    global _STATE
    kind, payload = reader_spec
    if kind == "columnar":
        from .format import Dataset

        reader = Dataset(payload)
    elif kind == "folder":
        reader = payload
    else:
        raise ValueError(f"unknown reader spec kind {kind!r}")
    writer = None
    if shm_args is not None:
        from .buffers import BufferPool, ShmSlotWriter

        writer = ShmSlotWriter(*shm_args)
        # Worker-local decode pages: the decoder writes into warm pooled
        # buffers, the slot write is one memcpy out of them, and the pages
        # recycle immediately after (pickling never sees them).
        if hasattr(decode_fn, "buffer_pool"):
            decode_fn.buffer_pool = BufferPool()
    _STATE = (kind, reader, decode_fn, columns, read_retries,
              retry_backoff_s, writer)


def _read_item(kind: str, reader, item, columns=None) -> pa.Table:
    if kind == "folder":
        # Folder reads always produce exactly {image, label}; nothing to
        # project.
        payloads, labels = [], []
        for i in np.asarray(item):
            path, label = reader[int(i)]
            with open(path, "rb") as f:
                payloads.append(f.read())
            labels.append(label)
        return pa.table(
            {"image": pa.array(payloads, pa.binary()),
             "label": pa.array(labels, pa.int64())}
        )
    if isinstance(item, np.ndarray):  # map-style: global-index take
        return reader.take(item, columns=columns)
    # iterable-style: list of ReadRange
    tables = [
        reader.read_range(r.fragment, r.start, r.stop, columns=columns)
        for r in item
    ]
    return pa.concat_tables(tables) if len(tables) > 1 else tables[0]


def _run_item(item):
    """One plan item → a tagged result: ``("shm", descriptor)`` when the
    batch rode a shared-memory slot, ``("raw", batch)`` when it must be
    pickled (shm off, non-dict batch, or no slot freed up in time)."""
    assert _STATE is not None, "worker not initialized"
    (kind, reader, decode_fn, columns, read_retries, backoff_s,
     writer) = _STATE
    retries = max(1, read_retries)
    last = None
    for attempt in range(retries):
        try:
            table = _read_item(kind, reader, item, columns)
            break
        except RETRYABLE_READ_ERRORS as exc:  # transient storage blip
            last = exc
            if attempt + 1 < retries:  # no pointless sleep after the last try
                import time

                time.sleep(backoff_s * (2**attempt))
    else:
        raise RuntimeError(
            f"worker read failed after {retries} attempts: {last}"
        ) from last
    batch = decode_fn(table)
    if writer is not None and isinstance(batch, dict):
        desc = writer.write_batch(batch)
        pool = getattr(decode_fn, "buffer_pool", None)
        if pool is not None:
            # Recycle the decode pages either way: after a slot write they
            # are free immediately; on the pickle fallback the executor's
            # return pickling still holds the dict, so the refcount guard
            # defers the actual reuse until that copy is done.
            pool.release_batch(batch)
        if desc is not None:
            return ("shm", desc)
    return ("raw", batch)


class _PoolState:
    """The mutable teardown target shared by :meth:`WorkerPool.shutdown`,
    :meth:`WorkerPool.resize`, and the GC-time finalizer. The finalizer must
    NOT close over the pool (that would pin it alive forever) and must NOT
    bind a fixed executor (``resize`` swaps executors) — so everything
    teardown needs lives here, behind one lock."""

    __slots__ = ("executor", "ring", "workers", "retired", "lanes", "lock")

    def __init__(self, executor, ring, workers: int):
        self.executor = executor
        self.ring = ring
        self.workers = workers
        # Executors retired by resize(), still draining their in-flight
        # items: (executor, joiner thread, worker count). shutdown() joins
        # these BEFORE unlinking shm segments — a retired worker mid-slot-
        # write racing ring.cleanup() was the shutdown-during-resize bug.
        self.retired: list = []
        # Dedicated lanes (ensure_lane): name -> (executor, worker count).
        # Same spawn context + initargs as the main executor, so lane
        # workers share the shm ring by session name exactly like resize's
        # replacement executors do.
        self.lanes: dict = {}
        self.lock = threading.Lock()


def _drain_retired(executor) -> None:
    """Retire-thread body: wait out the retired executor's in-flight items
    (their results are still owed to an ``imap`` consumer — dropping them
    would hole the plan), then join its workers."""
    executor.shutdown(wait=True, cancel_futures=False)


def _teardown_pool(state: _PoolState) -> None:
    """Shutdown body shared by :meth:`WorkerPool.shutdown` and the GC-time
    finalizer. Order matters: poison the slot queue FIRST (sized for every
    worker, current AND retired) so any worker blocked waiting for a free
    slot wakes and finishes, then join the retired executors' drains, then
    the live executor, and only then unlink the segments — a worker still
    writing a slot when the segment unlinks degrades that batch to the
    pickle fallback at best."""
    with state.lock:
        executor = state.executor
        ring = state.ring
        retired = list(state.retired)
        lanes = list(state.lanes.values())
        total_workers = (state.workers + sum(n for _, _, n in retired)
                         + sum(n for _, n in lanes))
    if ring is not None:
        ring.poison(total_workers)
    for old, joiner, _ in retired:
        joiner.join(timeout=30.0)
        # Idempotent (the joiner already ran shutdown); cancel_futures covers
        # a joiner that timed out wedged.
        old.shutdown(wait=True, cancel_futures=True)
    for lane_executor, _ in lanes:
        lane_executor.shutdown(wait=True, cancel_futures=True)
    executor.shutdown(wait=True, cancel_futures=True)
    if ring is not None:
        ring.cleanup()


class WorkerPool:
    """Persistent spawn-context process pool running read+decode.

    ``persistent_workers=True`` parity: create once, reuse across epochs
    (``/root/reference/lance_map_style.py:68``); workers keep their dataset
    handle and decoder warm between epochs.
    """

    def __init__(
        self,
        reader_spec: Tuple[str, object],
        decode_fn: Callable,
        num_workers: int,
        columns: Optional[Sequence[str]] = None,
        read_retries: int = 1,
        retry_backoff_s: float = 0.05,
        transport: str = "shm",
        buffer_pool=None,
        shm_slots: int = 0,
        shm_acquire_timeout_s: float = 10.0,
    ):
        """``read_retries > 1`` retries transient in-worker read failures
        (OSError) with exponential backoff — the data-service server passes
        its retry policy through so remote streams survive storage blips.

        ``transport="shm"`` (default) moves decoded batches through
        shared-memory ring slots (:mod:`.buffers`) instead of pickling
        them; ``"pickle"`` is the legacy path (and the automatic fallback
        when POSIX shm is unavailable). ``buffer_pool`` receives the
        consumer-side copies so pages recycle across batches; ``shm_slots``
        sizes the ring (default ``2 × num_workers`` — one slot per
        in-flight item at imap's default window)."""
        if num_workers < 1:
            raise ValueError("WorkerPool needs num_workers >= 1")
        if transport not in ("shm", "pickle"):
            raise ValueError(
                f"transport must be 'shm' or 'pickle', got {transport!r}"
            )
        self.num_workers = num_workers
        self.columns = list(columns) if columns is not None else None
        self.buffer_pool = buffer_pool
        ctx = mp.get_context("spawn")
        self._ring = None
        if transport == "shm":
            from .buffers import ShmRing, shm_available

            if shm_available():
                self._ring = ShmRing(
                    shm_slots or 2 * num_workers, ctx,
                    acquire_timeout_s=shm_acquire_timeout_s,
                )
            else:
                import warnings

                warnings.warn(
                    "POSIX shared memory unavailable — WorkerPool falling "
                    "back to the pickle transport (every decoded batch is "
                    "serialised across the IPC boundary)",
                    stacklevel=2,
                )
        self.transport = "shm" if self._ring is not None else "pickle"
        shm_args = self._ring.writer_args() if self._ring is not None else None
        # Spawn, not fork: fork would inherit locks/ctypes handles mid-state —
        # the exact hazard upstream's SafeLanceDataset exists to avoid.
        # (shm_args carries an mp.Queue: initargs travel as spawn-time
        # Process arguments, the one context where that pickle is legal.)
        # Kept so resize() can build replacement executors with the same
        # worker environment.
        self._ctx = ctx
        self._initargs = (reader_spec, decode_fn,
                          list(columns) if columns is not None else None,
                          read_retries, retry_backoff_s, shm_args)
        self._pool = ProcessPoolExecutor(
            max_workers=num_workers,
            mp_context=ctx,
            initializer=_init_worker,
            initargs=self._initargs,
        )
        # Leak guard: if the owning trainer crashes (or simply drops the
        # pool without shutdown()), the finalizer still tears the executor
        # down at GC / interpreter exit — spawned decode processes never
        # outlive their parent as orphans and shm slots never outlive the
        # pool. Registered against a shared state holder (not `self`, which
        # the finalizer would pin alive forever; not the executor, which
        # resize() swaps out from under a long-lived pool).
        self._state = _PoolState(self._pool, self._ring, num_workers)
        self._finalizer = weakref.finalize(self, _teardown_pool, self._state)

    @property
    def closed(self) -> bool:
        return not self._finalizer.alive

    @property
    def dispatch_capacity(self) -> Optional[int]:
        """Hard ceiling on concurrently-held in-flight items, or None
        (pickle transport — unbounded). On the shm transport this is the
        ring's slot count: a dispatcher holding results out of order
        (the straggler scheduler) pins one slot per undelivered batch,
        so exceeding it wedges workers on slot acquire until the 10 s
        timeout drops them to the pickle fallback."""
        return self._ring.nslots if self._ring is not None else None

    def resize(self, num_workers: int) -> int:
        """Grow or shrink the decode pool to ``num_workers`` WITHOUT
        dropping in-flight batches — the autotuner's actuator.

        Mechanism: a fresh spawn-context executor replaces the live one, so
        every subsequent ``imap`` submission lands on the new width, while
        the old executor *retires*: a daemon joiner thread waits out its
        in-flight items (their results are still owed, in order, to the
        consumer's future deque) and joins its workers. The shm ring is
        shared by session name + token queue, so old and new workers
        interleave slot writes safely; the consumer acks tokens regardless
        of which executor produced the descriptor.

        Shutdown ordering (the regression this API shipped with a fix for):
        ``shutdown()`` joins every retired executor's drain BEFORE
        unlinking the shm segments, so a retired worker mid-slot-write can
        never race ``ring.cleanup()``.

        Note the ring's slot count is fixed at construction (default
        ``2 × initial workers``): growing far beyond the initial width
        still works, but workers then contend for slots — size
        ``shm_slots`` generously when a run expects to be autotuned up.

        Returns the applied worker count. No-op (same count) returns
        immediately.
        """
        if num_workers < 1:
            raise ValueError("WorkerPool needs num_workers >= 1")
        if self.closed:
            raise RuntimeError("WorkerPool is shut down")
        state = self._state
        with state.lock:
            if num_workers == state.workers:
                return num_workers
            old = state.executor
            old_workers = state.workers
            new = ProcessPoolExecutor(
                max_workers=num_workers,
                mp_context=self._ctx,
                initializer=_init_worker,
                initargs=self._initargs,
            )
            state.executor = new
            state.workers = num_workers
            # Handle swap is GIL-atomic; imap reads it per submission, so
            # pending futures from the old executor and new submissions on
            # the new one interleave in the consumer's deque in plan order.
            self._pool = new  # ldt: ignore[LDT1002] -- atomic handle swap under state.lock; imap's per-submit read tolerates either executor
            self.num_workers = num_workers  # ldt: ignore[LDT1002] -- monotonic int swap, advisory reads only
            joiner = threading.Thread(
                target=_drain_retired, args=(old,), daemon=True,
                name="ldt-workerpool-retire",
            )
            state.retired.append((old, joiner, old_workers))
            joiner.start()
        default_registry().counter("workers_resizes_total").inc()
        default_registry().gauge("workers_pool_size").set(num_workers)
        return num_workers

    def tunables(self):
        """The autotuner's knob: decode worker count, bounded by the host's
        core count (growing decode processes past the cores that would run
        them only adds contention)."""
        from ..tune.tunable import Tunable

        return [Tunable(
            "workers",
            lambda: self.num_workers,
            self.resize,
            lo=1,
            hi=max(2, os.cpu_count() or 2, self.num_workers),
            doc="decode worker processes (WorkerPool.resize)",
        )]

    def imap(self, items: Iterable, window: int = 0) -> Iterator[dict]:
        """Ordered streaming map: results yielded in submission order, at most
        ``window`` items in flight (default: 2× workers).

        On iterator abandonment (generator ``close()``) or a raised decode
        error, in-flight futures are cancelled so the pool drains instead of
        decoding an epoch nobody will consume; the pool itself stays warm for
        the next epoch (``persistent_workers`` parity) — only
        :meth:`shutdown` / context-manager exit / GC tears it down.

        Telemetry: each head-of-line result wait lands in the
        ``workers_result_wait_ms`` histogram (process registry) — near-zero
        means workers outrun the consumer, sustained large values mean the
        pool (or the IPC pickling) is the bottleneck.
        """
        if self.closed:
            raise RuntimeError("WorkerPool is shut down")
        window = window or 2 * self.num_workers
        wait_hist = default_registry().histogram("workers_result_wait_ms")

        def _result(fut):
            t0 = time.monotonic_ns()
            out = fut.result()
            wait_hist.observe((time.monotonic_ns() - t0) / 1e6)
            return self._unwrap(out)

        it = iter(items)
        pending: deque = deque()
        try:
            for item in it:
                pending.append(self._submit(item))
                if len(pending) >= window:
                    yield _result(pending.popleft())
            while pending:
                yield _result(pending.popleft())
        finally:
            self.abandon(pending)

    def abandon(self, futs) -> None:
        """Hand back in-flight futures nobody will consume (generator
        close, decode error): cancel what hasn't started; running/done
        futures may hold shm slot tokens — reclaim them (non-blocking:
        the pool is persistent across epochs, so a lost token would
        shrink the ring forever; a blocking wait here would stall
        generator close behind in-flight decodes). Shared by
        :meth:`imap` and the straggler scheduler's dispatch loop."""
        for fut in futs:
            if not fut.cancel() and self._ring is not None:
                fut.add_done_callback(self._reclaim_slot)

    def ensure_lane(self, lane: str, num_workers: int = 1) -> int:
        """Create (idempotently) a dedicated named lane: a second
        executor sharing this pool's spawn context, initargs, and shm
        ring — the straggler scheduler's heavy lane, so one predicted
        straggler never queues behind another. Sized once at first use;
        torn down with the pool (:func:`_teardown_pool` poisons the slot
        queue for lane workers too). Returns the lane's worker count."""
        if num_workers < 1:
            raise ValueError("lane needs num_workers >= 1")
        if self.closed:
            raise RuntimeError("WorkerPool is shut down")
        state = self._state
        with state.lock:
            existing = state.lanes.get(lane)
            if existing is not None:
                return existing[1]
            executor = ProcessPoolExecutor(
                max_workers=num_workers,
                mp_context=self._ctx,
                initializer=_init_worker,
                initargs=self._initargs,
            )
            state.lanes[lane] = (executor, num_workers)
        default_registry().gauge("workers_lane_size").set(num_workers)
        return num_workers

    def submit_lane(self, item, lane: str = "default"):
        """Submit one plan item to a named lane (``"default"`` is the
        main executor — identical to the submission path :meth:`imap`
        uses). Non-default lanes must exist (:meth:`ensure_lane`)."""
        if lane == "default":
            return self._submit(item)
        with self._state.lock:
            entry = self._state.lanes.get(lane)
            if entry is None:
                raise ValueError(
                    f"unknown lane {lane!r} — call ensure_lane first"
                )
            return entry[0].submit(_run_item, item)

    def _submit(self, item):
        """Submit under the pool-state lock: ``resize`` swaps the executor
        and then (from its joiner thread, after releasing the lock) shuts
        the old one down — an unlocked read-then-submit could land on the
        retired executor *after* its shutdown and raise. Serialized here, a
        submit either lands on the old executor before the swap (its work
        item is already enqueued, so the retire drain completes it) or on
        the new one after."""
        with self._state.lock:
            return self._state.executor.submit(_run_item, item)

    def _unwrap(self, out):
        """Tagged worker result → batch dict (shm read + slot ack, or the
        pickled payload on the fallback path)."""
        if isinstance(out, tuple) and len(out) == 2 and out[0] == "shm":
            if leaktrack.enabled():
                # Parent-side token custody starts when the descriptor
                # lands here and ends at read_batch's ack-put (or
                # release_token on the abandon path) — the LDT1201 shm
                # witness half.
                desc = out[1]
                leaktrack.track_acquire(
                    "shm-token",
                    (self._ring.session, desc["slot"], desc["gen"]),
                )
            return self._ring.read_batch(out[1], self.buffer_pool)
        if isinstance(out, tuple) and len(out) == 2 and out[0] == "raw":
            if self._ring is not None:
                self._ring.count_fallback()
            return out[1]
        return out  # pre-tag worker build (defensive)

    def _reclaim_slot(self, fut) -> None:
        """Done-callback for abandoned in-flight futures: return the shm
        token their descriptor holds. Runs on the executor's collector
        thread the moment the result lands (immediately for already-done
        futures); release_token is a no-op after ring cleanup."""
        try:
            out = fut.result(timeout=0)
        except Exception:
            return  # worker error/cancel: shutdown's cleanup unlinks slots
        if isinstance(out, tuple) and len(out) == 2 and out[0] == "shm":
            self._ring.release_token(out[1])

    def shutdown(self) -> None:
        # wait=True: join the workers — abandoning spawn children mid-task
        # makes them die noisily ("Fatal Python error") at interpreter exit.
        # Routed through the finalizer so shutdown is idempotent and the
        # GC-time teardown never runs twice.
        self._finalizer()

    def __enter__(self) -> "WorkerPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
