"""Process-pool decode workers — the ``get_safe_loader`` equivalent.

The reference's map-style path gets decode parallelism from torch DataLoader
worker *processes* running ``collate_fn``
(``/root/reference/lance_map_style.py:60-69``, ``num_workers=8``, spawn
context + ``persistent_workers`` at ``torch_version/map_style.py:63-74``),
via upstream's ``get_safe_loader`` — "Safe" because each worker must re-open
the native dataset handle rather than inherit it across ``fork``
(``README.md:24,60``; SURVEY.md §7 "fork-safe w.r.t. the native reader
handle").

Here the same capability is a :class:`WorkerPool`: N spawned processes, each
re-opening the columnar store by URI in its initializer (our ``Dataset``
handles are just memory-maps — cheap to re-open, nothing to inherit), running
read+decode for whole plan items and streaming results back **in plan order**
with a bounded in-flight window. The training process never touches a JPEG.

When to use which decode parallelism:

* ``num_workers=0`` (default): producer thread + native C++ decoder
  (:mod:`..native`) — the decode pool releases the GIL, so threads already
  scale across cores with zero IPC cost. Best when the native path is built.
  (Since r7 neither choice affects H2D: placement runs on the plane's own
  thread downstream of the pool, :mod:`.placement`.)
* ``num_workers>0``: process workers — true parallelism for *Python-bound*
  decode hooks (custom ``to_tensor_fn``/``collate_fn`` plugins that hold the
  GIL). With the default ``transport="shm"`` the decoded tensors cross the
  IPC boundary through ``multiprocessing.shared_memory`` ring slots
  (:mod:`.buffers`): the worker returns only a tiny ``(slot, shapes,
  dtypes, offsets)`` descriptor and the consumer copies once out of the
  mapped pages — replacing the old per-batch pickle (serialise + pipe
  write + pipe read + deserialise ≈ four full copies of ~38 MB of decoded
  uint8 per 512×224px batch). ``transport="pickle"`` keeps the old path
  (the A/B control arm; also the automatic fallback when POSIX shared
  memory is unavailable).
"""

from __future__ import annotations

import multiprocessing as mp
import time
import weakref
from collections import deque
from concurrent.futures import ProcessPoolExecutor
from typing import Callable, Iterable, Iterator, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from ..obs.registry import default_registry

__all__ = ["WorkerPool", "columnar_spec", "folder_spec", "RETRYABLE_READ_ERRORS"]

# The transient-read failure set shared by every retry surface (in-worker
# retries here, the data-service server's read_item): one definition so the
# policies cannot drift.
RETRYABLE_READ_ERRORS = (OSError, pa.ArrowInvalid)

# Per-worker state, set by the pool initializer (module-global because
# ProcessPoolExecutor task functions must be importable module-level names).
_STATE: Optional[tuple] = None


def columnar_spec(uri: str) -> Tuple[str, object]:
    """Reader spec for a columnar dataset: workers re-open by URI."""
    return ("columnar", str(uri))


def folder_spec(samples: Sequence[Tuple[str, int]]) -> Tuple[str, object]:
    """Reader spec for the folder control arm: (path, label) samples."""
    return ("folder", list(samples))


def _init_worker(reader_spec, decode_fn, columns=None,
                 read_retries=1, retry_backoff_s=0.05,
                 shm_args=None) -> None:
    global _STATE
    kind, payload = reader_spec
    if kind == "columnar":
        from .format import Dataset

        reader = Dataset(payload)
    elif kind == "folder":
        reader = payload
    else:
        raise ValueError(f"unknown reader spec kind {kind!r}")
    writer = None
    if shm_args is not None:
        from .buffers import BufferPool, ShmSlotWriter

        writer = ShmSlotWriter(*shm_args)
        # Worker-local decode pages: the decoder writes into warm pooled
        # buffers, the slot write is one memcpy out of them, and the pages
        # recycle immediately after (pickling never sees them).
        if hasattr(decode_fn, "buffer_pool"):
            decode_fn.buffer_pool = BufferPool()
    _STATE = (kind, reader, decode_fn, columns, read_retries,
              retry_backoff_s, writer)


def _read_item(kind: str, reader, item, columns=None) -> pa.Table:
    if kind == "folder":
        # Folder reads always produce exactly {image, label}; nothing to
        # project.
        payloads, labels = [], []
        for i in np.asarray(item):
            path, label = reader[int(i)]
            with open(path, "rb") as f:
                payloads.append(f.read())
            labels.append(label)
        return pa.table(
            {"image": pa.array(payloads, pa.binary()),
             "label": pa.array(labels, pa.int64())}
        )
    if isinstance(item, np.ndarray):  # map-style: global-index take
        return reader.take(item, columns=columns)
    # iterable-style: list of ReadRange
    tables = [
        reader.read_range(r.fragment, r.start, r.stop, columns=columns)
        for r in item
    ]
    return pa.concat_tables(tables) if len(tables) > 1 else tables[0]


def _run_item(item):
    """One plan item → a tagged result: ``("shm", descriptor)`` when the
    batch rode a shared-memory slot, ``("raw", batch)`` when it must be
    pickled (shm off, non-dict batch, or no slot freed up in time)."""
    assert _STATE is not None, "worker not initialized"
    (kind, reader, decode_fn, columns, read_retries, backoff_s,
     writer) = _STATE
    retries = max(1, read_retries)
    last = None
    for attempt in range(retries):
        try:
            table = _read_item(kind, reader, item, columns)
            break
        except RETRYABLE_READ_ERRORS as exc:  # transient storage blip
            last = exc
            if attempt + 1 < retries:  # no pointless sleep after the last try
                import time

                time.sleep(backoff_s * (2**attempt))
    else:
        raise RuntimeError(
            f"worker read failed after {retries} attempts: {last}"
        ) from last
    batch = decode_fn(table)
    if writer is not None and isinstance(batch, dict):
        desc = writer.write_batch(batch)
        pool = getattr(decode_fn, "buffer_pool", None)
        if pool is not None:
            # Recycle the decode pages either way: after a slot write they
            # are free immediately; on the pickle fallback the executor's
            # return pickling still holds the dict, so the refcount guard
            # defers the actual reuse until that copy is done.
            pool.release_batch(batch)
        if desc is not None:
            return ("shm", desc)
    return ("raw", batch)


def _teardown_pool(executor, ring, num_workers: int) -> None:
    """Shutdown body shared by :meth:`WorkerPool.shutdown` and the GC-time
    finalizer. Order matters: poison the slot queue FIRST so a worker
    blocked waiting for a free slot wakes and finishes (executor shutdown
    joins workers), then unlink the segments."""
    if ring is not None:
        ring.poison(num_workers)
    executor.shutdown(wait=True, cancel_futures=True)
    if ring is not None:
        ring.cleanup()


class WorkerPool:
    """Persistent spawn-context process pool running read+decode.

    ``persistent_workers=True`` parity: create once, reuse across epochs
    (``/root/reference/lance_map_style.py:68``); workers keep their dataset
    handle and decoder warm between epochs.
    """

    def __init__(
        self,
        reader_spec: Tuple[str, object],
        decode_fn: Callable,
        num_workers: int,
        columns: Optional[Sequence[str]] = None,
        read_retries: int = 1,
        retry_backoff_s: float = 0.05,
        transport: str = "shm",
        buffer_pool=None,
        shm_slots: int = 0,
        shm_acquire_timeout_s: float = 10.0,
    ):
        """``read_retries > 1`` retries transient in-worker read failures
        (OSError) with exponential backoff — the data-service server passes
        its retry policy through so remote streams survive storage blips.

        ``transport="shm"`` (default) moves decoded batches through
        shared-memory ring slots (:mod:`.buffers`) instead of pickling
        them; ``"pickle"`` is the legacy path (and the automatic fallback
        when POSIX shm is unavailable). ``buffer_pool`` receives the
        consumer-side copies so pages recycle across batches; ``shm_slots``
        sizes the ring (default ``2 × num_workers`` — one slot per
        in-flight item at imap's default window)."""
        if num_workers < 1:
            raise ValueError("WorkerPool needs num_workers >= 1")
        if transport not in ("shm", "pickle"):
            raise ValueError(
                f"transport must be 'shm' or 'pickle', got {transport!r}"
            )
        self.num_workers = num_workers
        self.columns = list(columns) if columns is not None else None
        self.buffer_pool = buffer_pool
        ctx = mp.get_context("spawn")
        self._ring = None
        if transport == "shm":
            from .buffers import ShmRing, shm_available

            if shm_available():
                self._ring = ShmRing(
                    shm_slots or 2 * num_workers, ctx,
                    acquire_timeout_s=shm_acquire_timeout_s,
                )
            else:
                import warnings

                warnings.warn(
                    "POSIX shared memory unavailable — WorkerPool falling "
                    "back to the pickle transport (every decoded batch is "
                    "serialised across the IPC boundary)",
                    stacklevel=2,
                )
        self.transport = "shm" if self._ring is not None else "pickle"
        shm_args = self._ring.writer_args() if self._ring is not None else None
        # Spawn, not fork: fork would inherit locks/ctypes handles mid-state —
        # the exact hazard upstream's SafeLanceDataset exists to avoid.
        # (shm_args carries an mp.Queue: initargs travel as spawn-time
        # Process arguments, the one context where that pickle is legal.)
        self._pool = ProcessPoolExecutor(
            max_workers=num_workers,
            mp_context=ctx,
            initializer=_init_worker,
            initargs=(reader_spec, decode_fn,
                      list(columns) if columns is not None else None,
                      read_retries, retry_backoff_s, shm_args),
        )
        # Leak guard: if the owning trainer crashes (or simply drops the
        # pool without shutdown()), the finalizer still tears the executor
        # down at GC / interpreter exit — spawned decode processes never
        # outlive their parent as orphans and shm slots never outlive the
        # pool. Registered against the executor/ring objects directly — a
        # finalizer closing over `self` would keep the pool alive forever.
        self._finalizer = weakref.finalize(
            self, _teardown_pool, self._pool, self._ring, num_workers,
        )

    @property
    def closed(self) -> bool:
        return not self._finalizer.alive

    def imap(self, items: Iterable, window: int = 0) -> Iterator[dict]:
        """Ordered streaming map: results yielded in submission order, at most
        ``window`` items in flight (default: 2× workers).

        On iterator abandonment (generator ``close()``) or a raised decode
        error, in-flight futures are cancelled so the pool drains instead of
        decoding an epoch nobody will consume; the pool itself stays warm for
        the next epoch (``persistent_workers`` parity) — only
        :meth:`shutdown` / context-manager exit / GC tears it down.

        Telemetry: each head-of-line result wait lands in the
        ``workers_result_wait_ms`` histogram (process registry) — near-zero
        means workers outrun the consumer, sustained large values mean the
        pool (or the IPC pickling) is the bottleneck.
        """
        if self.closed:
            raise RuntimeError("WorkerPool is shut down")
        window = window or 2 * self.num_workers
        wait_hist = default_registry().histogram("workers_result_wait_ms")

        def _result(fut):
            t0 = time.monotonic_ns()
            out = fut.result()
            wait_hist.observe((time.monotonic_ns() - t0) / 1e6)
            return self._unwrap(out)

        it = iter(items)
        pending: deque = deque()
        try:
            for item in it:
                pending.append(self._pool.submit(_run_item, item))
                if len(pending) >= window:
                    yield _result(pending.popleft())
            while pending:
                yield _result(pending.popleft())
        finally:
            for fut in pending:
                # Cancel what hasn't started; running/done futures may hold
                # shm slot tokens — reclaim them (non-blocking: the pool is
                # persistent across epochs, so a lost token would shrink
                # the ring forever; a blocking wait here would stall
                # generator close behind in-flight decodes).
                if not fut.cancel() and self._ring is not None:
                    fut.add_done_callback(self._reclaim_slot)

    def _unwrap(self, out):
        """Tagged worker result → batch dict (shm read + slot ack, or the
        pickled payload on the fallback path)."""
        if isinstance(out, tuple) and len(out) == 2 and out[0] == "shm":
            return self._ring.read_batch(out[1], self.buffer_pool)
        if isinstance(out, tuple) and len(out) == 2 and out[0] == "raw":
            if self._ring is not None:
                self._ring.count_fallback()
            return out[1]
        return out  # pre-tag worker build (defensive)

    def _reclaim_slot(self, fut) -> None:
        """Done-callback for abandoned in-flight futures: return the shm
        token their descriptor holds. Runs on the executor's collector
        thread the moment the result lands (immediately for already-done
        futures); release_token is a no-op after ring cleanup."""
        try:
            out = fut.result(timeout=0)
        except Exception:
            return  # worker error/cancel: shutdown's cleanup unlinks slots
        if isinstance(out, tuple) and len(out) == 2 and out[0] == "shm":
            self._ring.release_token(out[1])

    def shutdown(self) -> None:
        # wait=True: join the workers — abandoning spawn children mid-task
        # makes them die noisily ("Fatal Python error") at interpreter exit.
        # Routed through the finalizer so shutdown is idempotent and the
        # GC-time teardown never runs twice.
        self._finalizer()

    def __enter__(self) -> "WorkerPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
