"""Row-filter predicates for the columnar store.

Upstream Lance's scanner accepts SQL-ish row filters pushed down into the
fragment reads; the reference never uses them, but a training framework over
a columnar store needs subset training (eval splits by label, quality
thresholds, deduplicated shards) without rewriting the dataset. Here a
predicate is resolved to a **global row-index pool** once, up front
(:meth:`~.format.Dataset.filter_indices`), and the map-style sampler then
shards/permutes inside that pool — so the equal-step-count invariant the
distributed samplers guarantee (SURVEY.md §2.2) is preserved by
construction: every process sees the same pool and deals batches from it.

Accepted predicate forms, lowest-dependency first:

* a **string** in the mini-grammar ``column OP literal [& column OP
  literal ...]`` with OP in ``== != <= >= < >`` — e.g. ``"label < 50"``,
  ``"label >= 10 & label != 13"`` (conjunction only; this is the CLI's
  ``--filter`` surface),
* a **pyarrow.compute.Expression** — e.g. ``pc.field("label") < 50``,
* a **callable** ``table -> bool mask`` for arbitrary Python predicates.
"""

from __future__ import annotations

import re
from typing import Callable, Union

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

__all__ = ["parse_predicate", "predicate_mask", "Predicate"]

Predicate = Union[str, "pc.Expression", Callable[[pa.Table], np.ndarray]]

_COMPARISON = re.compile(
    r"^\s*([A-Za-z_][A-Za-z0-9_]*)\s*(==|!=|<=|>=|<|>)\s*(.+?)\s*$"
)

_OPS = {
    "==": lambda f, v: f == v,
    "!=": lambda f, v: f != v,
    "<": lambda f, v: f < v,
    "<=": lambda f, v: f <= v,
    ">": lambda f, v: f > v,
    ">=": lambda f, v: f >= v,
}


def _literal(text: str):
    if len(text) >= 2 and text[0] == text[-1] and text[0] in "'\"":
        return text[1:-1]
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        raise ValueError(
            f"unparseable literal {text!r} (int, float, or quoted string)"
        ) from None


def parse_predicate(text: str) -> "pc.Expression":
    """``"label < 50 & label != 13"`` → a pyarrow compute Expression."""
    terms = [t for t in text.split("&") if t.strip()]
    if not terms:
        raise ValueError(f"empty predicate {text!r}")
    expr = None
    for term in terms:
        m = _COMPARISON.match(term)
        if m is None:
            raise ValueError(
                f"bad predicate term {term!r} (expected 'column OP literal' "
                "with OP in == != <= >= < >)"
            )
        column, op, lit = m.groups()
        piece = _OPS[op](pc.field(column), _literal(lit))
        expr = piece if expr is None else (expr & piece)
    return expr


def predicate_mask(table: pa.Table, predicate: Predicate) -> np.ndarray:
    """Evaluate any accepted predicate form → boolean numpy mask over rows."""
    if isinstance(predicate, str):
        predicate = parse_predicate(predicate)
    if callable(predicate) and not isinstance(predicate, pc.Expression):
        mask = np.asarray(predicate(table), dtype=bool)
        if mask.shape != (table.num_rows,):
            raise ValueError(
                f"callable predicate returned shape {mask.shape}, expected "
                f"({table.num_rows},)"
            )
        return mask
    # Expression path: scan with the predicate as the FILTER but project only
    # the row-id column, so kept rows copy 8 bytes each — never the payload
    # columns (a JPEG column would otherwise be materialised per kept row
    # just to be discarded). append_column is metadata-only (zero-copy).
    import pyarrow.dataset as pads

    ids = pa.array(np.arange(table.num_rows, dtype=np.int64))
    kept = (
        pads.dataset(table.append_column("__row__", ids))
        .scanner(columns=["__row__"], filter=predicate)
        .to_table()
    )
    mask = np.zeros(table.num_rows, dtype=bool)
    mask[kept.column("__row__").to_numpy()] = True
    return mask
