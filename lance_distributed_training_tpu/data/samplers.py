"""Sampler plans — pure functions from fragment row-counts to read plans.

The reference uses three upstream Lance samplers plus torch's
``DistributedSampler`` (SURVEY.md §2.2):

* ``ShardedBatchSampler(rank, world_size)`` — batch-level round-robin row
  ranges, perfectly balanced (``/root/reference/lance_iterable.py:62-63``,
  ``README.md:127,257-271``),
* ``ShardedFragmentSampler(rank, world_size, pad=True)`` — strided whole
  fragments per rank; I/O-optimal, but unbalanced fragments deadlock the
  collective (``README.md:140-157``, crash log ``:162-254``),
* ``FullScanSampler()`` — not DP-aware, every process scans everything
  (``lance_iterable.py:66-67``),
* torch ``DistributedSampler`` for the map-style path
  (``lance_map_style.py:56-58``).

TPU-native re-design: samplers here are **pure functions** producing explicit
*plans* (lists of :class:`ReadRange` per step), decoupled from any reader.
This unifies the reference's sampler⇄dataset coupling rule
(``README.md:274-284``) — the same plan feeds the streaming reader (iterable
path) or the random-access ``take`` path (map-style).

Each returned plan is **per-process**: step ``s`` of process ``p`` is
``plan[s]``. The load-bearing invariant — every process emits the *same*
number of steps, each of the *same* row count — is what prevents the
collective-deadlock failure class on TPU exactly as on NCCL (unequal step
counts hang ``psum``; SURVEY.md §2.4). :func:`assert_equal_step_counts`
checks it statically at pipeline-build time (SURVEY.md §5 "race detection").
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import numpy as np

__all__ = [
    "ReadRange",
    "full_scan_plan",
    "sharded_batch_plan",
    "sharded_fragment_plan",
    "distributed_indices",
    "distributed_index_batches",
    "padded_eval_index_batches",
    "assert_equal_step_counts",
    "make_plan",
    "slice_plan",
]


class ReadRange(NamedTuple):
    """Rows ``[start, stop)`` of one fragment."""

    fragment: int
    start: int
    stop: int

    @property
    def num_rows(self) -> int:
        return self.stop - self.start


Plan = list[list[ReadRange]]  # plan[step] = ranges forming that step's batch


def _global_to_ranges(
    fragment_rows: Sequence[int], start: int, stop: int
) -> list[ReadRange]:
    """Global row span [start, stop) → per-fragment ranges (may straddle)."""
    offsets = np.concatenate([[0], np.cumsum(fragment_rows)])
    ranges = []
    for fid in range(len(fragment_rows)):
        lo = max(start, int(offsets[fid]))
        hi = min(stop, int(offsets[fid + 1]))
        if lo < hi:
            ranges.append(ReadRange(fid, lo - int(offsets[fid]), hi - int(offsets[fid])))
    return ranges


def full_scan_plan(
    fragment_rows: Sequence[int],
    batch_size: int,
    *,
    drop_last: bool = False,
) -> Plan:
    """Every process scans the full dataset sequentially.

    Parity: ``FullScanSampler`` — "not DP-aware", single-device eval/debug
    (``/root/reference/README.md:126,130-138``).
    """
    total = int(sum(fragment_rows))
    plan: Plan = []
    for start in range(0, total, batch_size):
        stop = min(start + batch_size, total)
        if drop_last and stop - start < batch_size:
            break
        plan.append(_global_to_ranges(fragment_rows, start, stop))
    return plan


def sharded_batch_plan(
    fragment_rows: Sequence[int],
    batch_size: int,
    process_index: int,
    process_count: int,
    *,
    shuffle: bool = False,
    seed: int = 0,
    epoch: int = 0,
) -> Plan:
    """Batch-level round-robin sharding — balanced by construction.

    Parity: ``ShardedBatchSampler(rank, world_size)`` — global batches dealt
    round-robin (rank 0 → batches 0, 2, 4, …), "perfectly balanced … safest
    choice", at the cost of row-range reads instead of whole-fragment reads
    (``/root/reference/README.md:127,257-271``).

    The trailing partial global batch and the trailing un-deal-able full
    batches are dropped so every process gets exactly the same step count.

    ``shuffle=True`` goes beyond the reference (Lance samplers are
    deterministic every epoch — no ``set_epoch`` anywhere in
    ``lance_iterable.py``): the *batch order* is permuted with a
    ``seed + epoch``-seeded RNG. Every process draws the identical
    permutation, so batches stay disjoint and step counts stay equal (the
    deadlock invariant); rows within a batch keep their storage order, so
    reads remain contiguous ranges.
    """
    _check_topology(process_index, process_count)
    total = int(sum(fragment_rows))
    num_batches = total // batch_size  # drop ragged tail
    usable = (num_batches // process_count) * process_count
    order = np.arange(usable)
    if shuffle:
        order = np.random.default_rng(seed + epoch).permutation(usable)
    plan: Plan = []
    for b in order[process_index::process_count]:
        plan.append(
            _global_to_ranges(fragment_rows, int(b) * batch_size,
                              (int(b) + 1) * batch_size)
        )
    return plan


def sharded_fragment_plan(
    fragment_rows: Sequence[int],
    batch_size: int,
    process_index: int,
    process_count: int,
    *,
    pad: bool = True,
) -> Plan:
    """Fragment-level strided sharding — I/O-optimal sequential reads.

    Parity: ``ShardedFragmentSampler(rank, world_size, pad=True)`` — process
    ``k`` reads fragments ``k, k + world_size, …`` sequentially
    (``/root/reference/README.md:128,140-157``). With unequal fragment sizes
    the raw assignment is unbalanced; the reference documents the resulting
    NCCL-watchdog deadlock (``README.md:162-254``). ``pad=True`` equalises
    step counts across processes by wrapping around the process's own rows
    (repeating early rows), so every process emits
    ``max_p ceil(rows_p / batch_size)`` identical-size batches. ``pad=False``
    truncates every process to ``min_p floor(rows_p / batch_size)`` steps —
    balanced by dropping data instead of repeating it.
    """
    _check_topology(process_index, process_count)
    num_fragments = len(fragment_rows)
    per_proc_rows = [
        sum(fragment_rows[f] for f in range(p, num_fragments, process_count))
        for p in range(process_count)
    ]
    my_fragments = list(range(process_index, num_fragments, process_count))
    my_rows = per_proc_rows[process_index]

    if pad:
        steps = max(-(-rows // batch_size) for rows in per_proc_rows)  # ceil
    else:
        steps = min(rows // batch_size for rows in per_proc_rows)
    if steps == 0:
        return []
    if my_rows == 0:
        # A process with zero fragments still must emit `steps` batches or the
        # collective hangs; wrap reads around fragment 0 of the whole dataset.
        my_fragments = [fid for fid in range(num_fragments) if fragment_rows[fid] > 0]
        my_rows = sum(fragment_rows[f] for f in my_fragments)
        if my_rows == 0:
            raise ValueError("dataset has no rows")

    # Local concatenated row space over my fragments, wrap-around for padding.
    local_rows = [fragment_rows[f] for f in my_fragments]
    local_offsets = np.concatenate([[0], np.cumsum(local_rows)])

    def local_range(start: int, stop: int) -> list[ReadRange]:
        out = []
        for i, fid in enumerate(my_fragments):
            lo = max(start, int(local_offsets[i]))
            hi = min(stop, int(local_offsets[i + 1]))
            if lo < hi:
                out.append(
                    ReadRange(fid, lo - int(local_offsets[i]), hi - int(local_offsets[i]))
                )
        return out

    plan: Plan = []
    for s in range(steps):
        start = s * batch_size
        ranges: list[ReadRange] = []
        need = batch_size
        cursor = start % my_rows if my_rows else 0
        # Wrap as many times as needed (tiny datasets may wrap repeatedly).
        while need > 0:
            span = min(need, my_rows - cursor)
            ranges.extend(local_range(cursor, cursor + span))
            need -= span
            cursor = (cursor + span) % my_rows
        plan.append(ranges)
    return plan


def distributed_indices(
    num_rows: int,
    process_index: int,
    process_count: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    drop_last: bool = False,
) -> np.ndarray:
    """Map-style index sharding — torch ``DistributedSampler`` semantics.

    Parity: ``DistributedSampler(dataset, num_replicas, rank, shuffle=True)``
    (``/root/reference/lance_map_style.py:56-58``) including ``set_epoch``
    reshuffling (``lance_map_style.py:85-86``): the permutation is seeded by
    ``seed + epoch``; rows are padded by wrap-around (or dropped with
    ``drop_last``) to a multiple of ``process_count`` and dealt
    ``indices[rank::world_size]``.
    """
    _check_topology(process_index, process_count)
    if shuffle:
        rng = np.random.default_rng(seed + epoch)
        indices = rng.permutation(num_rows)
    else:
        indices = np.arange(num_rows)
    if drop_last:
        usable = (num_rows // process_count) * process_count
        indices = indices[:usable]
    else:
        target = -(-num_rows // process_count) * process_count
        if target > num_rows:
            indices = np.concatenate([indices, indices[: target - num_rows]])
    return indices[process_index::process_count]


def distributed_index_batches(
    num_rows: int,
    batch_size: int,
    process_index: int,
    process_count: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    drop_last: bool = True,
) -> list[np.ndarray]:
    """:func:`distributed_indices` sliced into per-step batches — the shared
    map-style batch-formation used by both the columnar and folder pipelines."""
    indices = distributed_indices(
        num_rows,
        process_index,
        process_count,
        shuffle=shuffle,
        seed=seed,
        epoch=epoch,
        drop_last=drop_last,
    )
    n = len(indices)
    steps = n // batch_size if drop_last else -(-n // batch_size)
    return [indices[s * batch_size : (s + 1) * batch_size] for s in range(steps)]


def padded_eval_index_batches(
    num_rows: int,
    global_batch: int,
    process_index: int,
    process_count: int,
    *,
    index_pool: Optional[np.ndarray] = None,
) -> list[tuple[np.ndarray, np.ndarray]]:
    """Full-coverage eval plan: every row exactly once, ONE compiled shape.

    The train-side samplers trade the ragged tail away (batch plans drop it;
    ``full_scan_plan`` keeps it ragged, costing one extra XLA compile per
    eval shape). Eval wants neither: the tail batch is padded back to
    ``global_batch`` by wrap-around rows carried with weight 0.0, so the
    weighted metric counts each real row exactly once and the jitted eval
    step sees a single static shape. Every process gets the same batch
    count by construction (the deadlock invariant).

    Returns THIS process's ``(indices, weights)`` per step: its
    ``global_batch // process_count`` slice of each global batch. With
    ``index_pool`` (row filters / val splits) positions index into the pool.
    """
    _check_topology(process_index, process_count)
    per_process, rem = divmod(global_batch, process_count)
    if rem:
        raise ValueError(
            f"global batch {global_batch} not divisible by "
            f"{process_count} processes"
        )
    if num_rows <= 0:
        return []
    n_batches = -(-num_rows // global_batch)
    padded = n_batches * global_batch
    pos = np.arange(padded) % num_rows
    idx = index_pool[pos] if index_pool is not None else pos
    weights = (np.arange(padded) < num_rows).astype(np.float32)
    out = []
    for b in range(n_batches):
        lo = b * global_batch + process_index * per_process
        hi = lo + per_process
        out.append((idx[lo:hi], weights[lo:hi]))
    return out


def make_plan(
    sampler_type: str,
    fragment_rows: Sequence[int],
    batch_size: int,
    process_index: int,
    process_count: int,
    *,
    pad: bool = True,
    shuffle: bool = False,
    seed: int = 0,
    epoch: int = 0,
) -> Plan:
    """Dispatch by name — parity with ``get_sampler``'s string dispatch
    (``/root/reference/lance_iterable.py:61-69``). ``shuffle`` applies to the
    batch sampler only (epoch batch-order reshuffle, identical on every
    process); requesting it with another sampler raises rather than silently
    replaying the same order every epoch."""
    if shuffle and sampler_type not in ("batch", "sharded_batch"):
        raise ValueError(
            f"shuffle=True supports sampler_type='batch' only (fragment "
            f"plans read whole fragments sequentially; full scans are "
            f"eval-only) — got {sampler_type!r}"
        )
    if sampler_type in ("batch", "sharded_batch"):
        return sharded_batch_plan(
            fragment_rows, batch_size, process_index, process_count,
            shuffle=shuffle, seed=seed, epoch=epoch,
        )
    if sampler_type in ("fragment", "sharded_fragment"):
        return sharded_fragment_plan(
            fragment_rows, batch_size, process_index, process_count, pad=pad
        )
    if sampler_type in ("full", "full_scan"):
        return full_scan_plan(fragment_rows, batch_size)
    raise ValueError(f"Invalid sampler type: {sampler_type}")


def assert_equal_step_counts(
    plans: Sequence[Plan], batch_size: Optional[int] = None
) -> None:
    """Static deadlock check: all per-process plans must agree on step count
    and per-step row count.

    This is the build-time guard against the reference's documented failure
    mode — fragment imbalance → ranks disagree on collective count → NCCL
    watchdog SIGABRT (``/root/reference/README.md:159-254``). On TPU the same
    imbalance hangs the XLA collective, so the check runs before training.
    """
    counts = [len(p) for p in plans]
    if len(set(counts)) > 1:
        raise RuntimeError(
            f"deadlock hazard: per-process step counts differ: {counts}. "
            "Unbalanced sharding (see reference README.md:140-157); use "
            "sharded_batch_plan or pad=True."
        )
    for step in range(counts[0] if counts else 0):
        rows = [sum(r.num_rows for r in plan[step]) for plan in plans]
        if len(set(rows)) > 1:
            raise RuntimeError(
                f"deadlock hazard: step {step} row counts differ across "
                f"processes: {rows}"
            )
        if batch_size is not None and rows and rows[0] != batch_size:
            raise RuntimeError(
                f"step {step} rows {rows[0]} != batch_size {batch_size}"
            )


def slice_plan(plan: Sequence, start_step: int) -> list:
    """The tail of a per-process plan from ``start_step`` — the resume
    cursor applied to the work list.

    Because every plan here is a pure function of (dataset, sampler, batch,
    shard, seed, epoch), a restarted process rebuilds the IDENTICAL plan and
    slicing it at the cursor yields exactly the not-yet-consumed batches:
    this is the invariant the loader ``state_dict()/load_state_dict()``
    contract (``data/pipeline.py``) rests on, and what makes a
    mid-epoch checkpoint resume bit-identical to the uninterrupted run.
    ``start_step == len(plan)`` is valid (a checkpoint taken on the last
    batch resumes into an empty tail); beyond it is a corrupt cursor and
    raises rather than silently re-serving from 0.
    """
    if not 0 <= start_step <= len(plan):
        raise ValueError(
            f"resume cursor {start_step} outside plan of {len(plan)} steps"
        )
    return list(plan[start_step:])


def _check_topology(process_index: int, process_count: int) -> None:
    if process_count < 1 or not (0 <= process_index < process_count):
        raise ValueError(
            f"invalid topology: process {process_index} of {process_count}"
        )
